"""Tools tests: DDL round-trip, REST endpoint + cursors, CLI, PinotFS."""
import json

import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema


class TestDdl:
    def test_create_show_drop(self):
        eng = QueryEngine()
        eng.sql(
            "CREATE TABLE orders ("
            "  city STRING,"
            "  tags STRING MV,"
            "  amount DOUBLE METRIC,"
            "  updated_at TIMESTAMP,"
            "  PRIMARY KEY (city)"
            ") WITH (invertedIndexColumns = 'city', timeColumnName = 'updated_at', retentionDays = '30')"
        )
        tables = eng.sql("SHOW TABLES")
        assert tables.rows == [("orders",)]
        state = eng.table("orders")
        assert state.schema.field("tags").single_value is False
        assert state.config.indexing.inverted_index_columns == ["city"]
        assert state.config.segments.retention_time_value == 30
        assert state.schema.primary_key_columns == ["city"]
        eng.sql("DROP TABLE orders")
        assert eng.sql("SHOW TABLES").rows == []

    def test_show_create_round_trip(self):
        from pinot_tpu.sql.ddl import parse_ddl

        eng = QueryEngine()
        ddl = (
            "CREATE TABLE rt (k STRING, v LONG METRIC, ts TIMESTAMP, PRIMARY KEY (k)) "
            "WITH (upsertMode = 'FULL', comparisonColumn = 'ts', timeColumnName = 'ts', "
            "streamType = 'memory', sortedColumn = 'k')"
        )
        eng.sql(ddl)
        shown = eng.sql("SHOW CREATE TABLE rt").rows[0][0]
        stmt = parse_ddl(shown)  # fixed point: re-parses to the same table
        assert stmt.schema.to_dict() == eng.table("rt").schema.to_dict()
        assert stmt.config.to_dict() == eng.table("rt").config.to_dict()

    def test_ddl_then_query(self):
        eng = QueryEngine()
        eng.sql("CREATE TABLE t (city STRING, v LONG METRIC)")
        state = eng.table("t")
        rng = np.random.default_rng(3)
        data = {"city": rng.choice(["a", "b"], 1000).astype(object), "v": rng.integers(0, 10, 1000)}
        eng.add_segment("t", build_segment(state.schema, data, "s0", table_config=state.config))
        res = eng.sql("SELECT city, SUM(v) FROM t GROUP BY city ORDER BY city")
        assert len(res.rows) == 2


class TestRestAndCursors:
    @pytest.fixture()
    def server(self):
        from pinot_tpu.cluster.rest import QueryServer

        eng = QueryEngine()
        eng.sql("CREATE TABLE t (city STRING, v LONG METRIC)")
        rng = np.random.default_rng(5)
        data = {"city": rng.choice(["sf", "nyc"], 5000).astype(object), "v": rng.integers(0, 100, 5000)}
        eng.add_segment("t", build_segment(eng.table("t").schema, data, "s0"))
        srv = QueryServer(eng).start()
        yield srv
        srv.stop()

    def test_query_endpoint(self, server):
        from pinot_tpu.cluster.rest import PinotClient

        client = PinotClient(f"http://127.0.0.1:{server.port}")
        resp = client.execute("SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city ORDER BY city")
        assert resp["resultTable"]["dataSchema"]["columnNames"] == ["city", "count(*)", "sum(v)"]
        assert len(resp["resultTable"]["rows"]) == 2
        assert resp["numDocsScanned"] == 5000
        assert resp["timeUsedMs"] > 0

    def test_health_and_metrics(self, server):
        import urllib.request

        with urllib.request.urlopen(f"http://127.0.0.1:{server.port}/health") as r:
            assert json.loads(r.read())["status"] == "OK"
        with urllib.request.urlopen(f"http://127.0.0.1:{server.port}/metrics") as r:
            snap = json.loads(r.read())
            assert "counters" in snap

    def test_error_payload(self, server):
        from pinot_tpu.cluster.rest import PinotClient
        import urllib.error

        client = PinotClient(f"http://127.0.0.1:{server.port}")
        with pytest.raises(urllib.error.HTTPError) as ei:
            client.execute("SELECT FROM nowhere")
        assert ei.value.code == 500

    def test_cursor_paging(self, server):
        from pinot_tpu.cluster.rest import PinotClient

        client = PinotClient(f"http://127.0.0.1:{server.port}")
        resp = client.execute("SELECT city, v FROM t LIMIT 250", useCursor=True, pageSize=100)
        cid = resp["cursorId"]
        assert len(resp["resultTable"]["rows"]) == 100
        p2 = client.fetch_cursor(cid, 2)
        assert p2["totalRows"] == 250
        assert p2["numPages"] == 3
        assert len(p2["rows"]) == 50
        all_rows = []
        for page in range(p2["numPages"]):
            all_rows.extend(client.fetch_cursor(cid, page)["rows"])
        assert len(all_rows) == 250


class TestCli:
    def test_create_segment_and_query(self, tmp_path, capsys):
        from pinot_tpu.tools.cli import main

        schema = Schema(
            "t",
            [
                FieldSpec("city", DataType.STRING),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            ],
        )
        sp = tmp_path / "schema.json"
        sp.write_text(schema.to_json())
        csv = tmp_path / "data.csv"
        csv.write_text("city,v\n" + "\n".join(f"c{i % 3},{i}" for i in range(300)))
        out = tmp_path / "seg"
        assert main(["create-segment", "--schema", str(sp), "--csv", str(csv), "--out", str(out)]) == 0
        assert main(["query", "--segments", str(out), "--sql", "SELECT COUNT(*), SUM(v) FROM t"]) == 0
        got = capsys.readouterr().out
        assert "300" in got and str(sum(range(300))) in got


class TestPinotFS:
    def test_local_fs_operations(self, tmp_path):
        from pinot_tpu.spi.filesystem import LocalPinotFS, fs_for_uri

        fs = fs_for_uri(str(tmp_path))
        assert isinstance(fs, LocalPinotFS)
        d = str(tmp_path / "a" / "b")
        fs.mkdir(d)
        f = str(tmp_path / "a" / "b" / "x.txt")
        with open(f, "w") as fh:
            fh.write("hello")
        assert fs.exists(f) and fs.length(f) == 5
        fs.copy(f, str(tmp_path / "a" / "y.txt"))
        fs.move(str(tmp_path / "a" / "y.txt"), str(tmp_path / "z.txt"))
        assert fs.exists(str(tmp_path / "z.txt"))
        files = fs.list_files(str(tmp_path), recursive=True)
        assert any(p.endswith("x.txt") for p in files)
        assert fs.delete(str(tmp_path / "z.txt"))

    def test_unknown_scheme(self):
        from pinot_tpu.spi.filesystem import fs_for_uri

        with pytest.raises(ValueError, match="no PinotFS registered"):
            fs_for_uri("s3://bucket/key")
