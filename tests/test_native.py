"""Native C++ component tests: roaring-style bitmap codec, CSV parser,
compressed inverted index, and byte-compat of the numpy fallback.
"""
import numpy as np
import pytest

from pinot_tpu.utils import bitmaps
from pinot_tpu.utils.native import available, get_lib


def _random_docs(rng, n_docs, density):
    n = int(n_docs * density)
    return np.sort(rng.choice(n_docs, size=n, replace=False)).astype(np.uint32)


class TestNativeBuilds:
    def test_toolchain_builds_library(self):
        # g++ is baked into the image; the native path must actually run in CI
        assert available(), "native library failed to build (g++ expected in image)"


class TestBitmapCodec:
    @pytest.mark.parametrize("density", [0.001, 0.02, 0.5])
    def test_roundtrip(self, density):
        rng = np.random.default_rng(3)
        docs = _random_docs(rng, 300_000, density)
        blob = bitmaps.compress(docs)
        words = np.zeros((300_000 + 31) // 32, dtype=np.uint32)
        card = bitmaps.decompress_into_words(blob, words)
        assert card == len(docs)
        got = np.nonzero(np.unpackbits(words.view(np.uint8), bitorder="little"))[0]
        assert np.array_equal(got, docs)
        assert bitmaps.cardinality(blob) == len(docs)

    def test_sparse_much_smaller_than_dense(self):
        rng = np.random.default_rng(5)
        docs = _random_docs(rng, 10_000_000, 0.0001)  # 1k docs over 10M
        blob = bitmaps.compress(docs)
        dense_bytes = 10_000_000 // 8
        assert len(blob) < dense_bytes / 100

    def test_python_fallback_byte_compatible(self, monkeypatch):
        """The numpy fallback must produce byte-identical output to C++."""
        if not available():
            pytest.skip("native lib unavailable; nothing to compare")
        rng = np.random.default_rng(7)
        docs = _random_docs(rng, 200_000, 0.05)
        native_blob = bitmaps.compress(docs)
        py_blob = bitmaps._compress_py(docs)
        assert native_blob == py_blob
        # and the python decoder reads the native blob
        words = np.zeros((200_000 + 31) // 32, dtype=np.uint32)
        assert bitmaps._decompress_py(native_blob, words) == len(docs)

    def test_empty(self):
        blob = bitmaps.compress(np.array([], dtype=np.uint32))
        words = np.zeros(10, dtype=np.uint32)
        assert bitmaps.decompress_into_words(blob, words) == 0
        assert words.sum() == 0


class TestCompressedInvertedIndex:
    def test_high_cardinality_inverted(self, tmp_path):
        from pinot_tpu.query.engine import QueryEngine
        from pinot_tpu.segment.builder import build_segment
        from pinot_tpu.segment.segment import ImmutableSegment
        from pinot_tpu.spi.config import IndexingConfig, TableConfig
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        rng = np.random.default_rng(11)
        n = 200_000
        # cardinality 80k > the 64k dense threshold -> compressed postings
        ids = rng.integers(0, 80_000, n)
        schema = Schema(
            "t", [FieldSpec("id", DataType.INT), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)]
        )
        cfg = TableConfig(name="t", indexing=IndexingConfig(inverted_index_columns=["id"]))
        seg = build_segment(schema, {"id": ids, "v": rng.integers(0, 10, n)}, "s0", table_config=cfg)
        assert type(seg.indexes["inverted"]["id"]).__name__ == "CompressedInvertedIndex"
        path = str(tmp_path / "s0")
        seg.save(path)
        loaded = ImmutableSegment.load(path)
        assert type(loaded.indexes["inverted"]["id"]).__name__ == "CompressedInvertedIndex"

        eng = QueryEngine()
        eng.register_table(schema, cfg)
        eng.add_segment("t", loaded)
        target = int(ids[123])
        res = eng.query(f"SELECT COUNT(*) FROM t WHERE id IN ({target}, 79999, 12345)")
        expected = int(np.isin(ids, [target, 79999, 12345]).sum())
        assert res.rows[0][0] == expected
        assert ("id", "inverted") in res.stats.filter_index_uses


class TestCsvParser:
    def test_csv_reader_with_quotes(self, tmp_path):
        from pinot_tpu.ingest import read_csv_columns

        p = tmp_path / "t.csv"
        p.write_text(
            'name,city,v\n"Smith, John",sf,1\nJane,"ny""c",2\n"multi\nline",la,3\n',
            encoding="utf-8",
        )
        cols = read_csv_columns(str(p))
        assert list(cols["name"]) == ["Smith, John", "Jane", "multi\nline"]
        assert list(cols["city"]) == ["sf", 'ny"c', "la"]
        assert list(cols["v"]) == ["1", "2", "3"]

    def test_csv_typed_with_schema(self, tmp_path):
        from pinot_tpu.ingest import read_csv_columns
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        schema = Schema(
            "t",
            [
                FieldSpec("name", DataType.STRING),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("p", DataType.DOUBLE, role=FieldRole.METRIC),
            ],
        )
        p = tmp_path / "t.csv"
        rows = [f"r{i},{i*3},{i/2}" for i in range(1000)]
        p.write_text("name,v,p\n" + "\n".join(rows) + "\n", encoding="utf-8")
        cols = read_csv_columns(str(p), schema=schema)
        assert cols["v"].dtype == np.int64
        assert cols["v"][999] == 2997
        assert abs(cols["p"][999] - 499.5) < 1e-9

    def test_csv_into_segment(self, tmp_path):
        from pinot_tpu.ingest import read_csv_columns
        from pinot_tpu.query.engine import QueryEngine
        from pinot_tpu.segment.builder import build_segment
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        schema = Schema(
            "t", [FieldSpec("city", DataType.STRING), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)]
        )
        p = tmp_path / "t.csv"
        p.write_text("city,v\n" + "\n".join(f"c{i%7},{i}" for i in range(5000)), encoding="utf-8")
        cols = read_csv_columns(str(p), schema=schema)
        eng = QueryEngine()
        eng.register_table(schema)
        eng.add_segment("t", build_segment(schema, cols, "s0"))
        res = eng.query("SELECT COUNT(*), SUM(v) FROM t")
        assert res.rows[0] == (5000, sum(range(5000)))

    def test_ragged_row_raises(self, tmp_path):
        from pinot_tpu.ingest import read_csv_columns

        p = tmp_path / "bad.csv"
        p.write_text("a,b\n1,2\n3\n", encoding="utf-8")
        with pytest.raises(ValueError, match="arity"):
            read_csv_columns(str(p))
