"""Literal-parameterized plan cache: shape-fingerprint keys + device params.

The tentpole contract: queries that differ ONLY in predicate literals
share one compiled kernel — the plan cache keys on the shape fingerprint
(literals canonicalized to parameter slots) and the literal values ride
in as device arguments.  These tests prove three things:

  * parity — a warm engine (cached plan, new params) returns bit-identical
    results to a cold engine and to sqlite, across EQ/IN/RANGE/NOT_IN on
    dict-encoded and raw columns, including NULLs and out-of-dictionary
    literals;
  * O(1) compiles — a 20-distinct-literal sweep records <= 2 compiles in
    DIST_AUDIT (literal-keyed caching recorded 20);
  * the broker result cache and the LRU primitive behave: hit/miss/
    invalidation on realtime append, TTL, bytes bound, and thread safety.
"""
import threading

import numpy as np
import pytest

from pinot_tpu.analysis.compile_audit import DIST_AUDIT
from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.utils.cache import LruCache

from golden import assert_same_rows, sqlite_from_data

N = 4000
CITIES = ["sf", "nyc", "chi", "la", "sea", "pdx"]


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("year", DataType.INT),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("price", DataType.DOUBLE, role=FieldRole.METRIC, nullable=True),
        ],
    )


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(23)
    data = {
        "city": rng.choice(CITIES, N).astype(object),
        "year": rng.integers(2000, 2012, N).astype(np.int32),
        "v": rng.integers(-100, 1000, N),
        "price": np.where(rng.random(N) < 0.2, np.nan, np.round(rng.random(N) * 50, 3)),
    }
    st = StackedTable.build(_schema(), data, 8)
    eng = DistributedEngine()
    eng.register_table("t", st)
    conn = sqlite_from_data("t", data)
    return eng, st, conn


# Literal families: every query in one family shares a shape, so on a warm
# engine all but the first ride the cached compiled kernel with fresh
# device parameters.  Families cover dict EQ (incl. the out-of-dictionary
# literal 'zzz'), dict IN / NOT_IN (different set sizes pad to one
# bucket), raw-numeric EQ / RANGE / IN, and a nullable raw RANGE.
FAMILIES = [
    [f"SELECT COUNT(*), SUM(v) FROM t WHERE city = '{c}'" for c in ("sf", "nyc", "la", "zzz")],
    [
        "SELECT city, SUM(v) FROM t WHERE city IN ('sf', 'nyc') GROUP BY city ORDER BY city",
        "SELECT city, SUM(v) FROM t WHERE city IN ('la', 'chi', 'sea') GROUP BY city ORDER BY city",
        "SELECT city, SUM(v) FROM t WHERE city IN ('pdx', 'zzz') GROUP BY city ORDER BY city",
    ],
    [
        "SELECT COUNT(*) FROM t WHERE city NOT IN ('sf')",
        "SELECT COUNT(*) FROM t WHERE city NOT IN ('nyc', 'la')",
    ],
    [f"SELECT COUNT(*), SUM(v) FROM t WHERE year = {y}" for y in (2003, 2011, 1999)],
    [
        f"SELECT year, COUNT(*) FROM t WHERE v BETWEEN {lo} AND {hi} "
        "GROUP BY year ORDER BY year LIMIT 50"
        for lo, hi in ((-50, 100), (0, 900), (500, 501))
    ],
    [
        "SELECT SUM(v) FROM t WHERE year IN (2001, 2002)",
        "SELECT SUM(v) FROM t WHERE year IN (2005, 2006, 2007, 2008)",
    ],
    [f"SELECT COUNT(price), SUM(v) FROM t WHERE price > {p}" for p in (10.5, 40.25, 49.9)],
]


class TestLiteralParity:
    @pytest.mark.parametrize("family", FAMILIES, ids=lambda f: f[0][30:70])
    def test_warm_engine_matches_sqlite_and_cold(self, env, family):
        eng, st, conn = env
        for sql in family:
            warm = eng.query(sql)
            cold_eng = DistributedEngine()
            cold_eng.register_table("t", st)
            cold = cold_eng.query(sql)
            exp = conn.execute(sql).fetchall()
            ordered = "ORDER BY" in sql
            assert_same_rows(warm.rows, exp, ordered=ordered)
            assert_same_rows(cold.rows, [tuple(r) for r in warm.rows], ordered=ordered)


class TestRecompileCount:
    def test_twenty_literal_sweep_compiles_at_most_twice(self, env):
        eng, st, conn = env
        sql_t = (
            "SELECT year, COUNT(*), SUM(v) FROM t "
            "WHERE v < {lit} GROUP BY year ORDER BY year LIMIT 50"
        )
        DIST_AUDIT.reset()
        for i in range(20):
            sql = sql_t.format(lit=-90 + i * 50)
            got = eng.query(sql)
            exp = conn.execute(sql).fetchall()
            assert_same_rows(got.rows, exp, ordered=True)
        assert sum(DIST_AUDIT.counts().values()) <= 2

    def test_limit_is_parameterized_but_honored(self, env):
        # LIMIT trims host-side -> rides a `?limit` slot, sharing one plan
        eng, _, _ = env
        DIST_AUDIT.reset()
        r3 = eng.query("SELECT city, SUM(v) FROM t GROUP BY city LIMIT 3")
        r4 = eng.query("SELECT city, SUM(v) FROM t GROUP BY city LIMIT 4")
        assert len(r3.rows) == 3 and len(r4.rows) == 4
        assert sum(DIST_AUDIT.counts().values()) <= 1

    def test_structure_affecting_option_stays_in_key(self, env):
        # maxDenseGroups flips the dense/sparse group-by plan -> fresh compile
        eng, _, conn = env
        sql = "SELECT city, COUNT(*) FROM t GROUP BY city ORDER BY city LIMIT 10"
        DIST_AUDIT.reset()
        dense = eng.query(sql)
        sparse = eng.query("SET maxDenseGroups = 2; " + sql)
        assert sum(DIST_AUDIT.counts().values()) >= 1  # sparse plan is its own entry
        exp = conn.execute(sql).fetchall()
        assert_same_rows(dense.rows, exp, ordered=True)
        assert_same_rows(sparse.rows, exp, ordered=True)


class TestBrokerResultCache:
    def _realtime_cluster(self, tmp_path):
        from pinot_tpu.cluster import Broker, Coordinator, ServerInstance
        from pinot_tpu.realtime import InMemoryStream
        from pinot_tpu.spi.config import SegmentsConfig, StreamConfig, TableConfig

        coord = Coordinator(replication=1)
        coord.register_server(ServerInstance("s0"))
        stream = InMemoryStream(1)
        cfg = TableConfig(
            name="rt",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=1000),
        )
        schema = Schema(
            "rt",
            [
                FieldSpec("city", DataType.STRING),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
        )
        coord.add_realtime_table(schema, cfg, str(tmp_path / "rt"), stream=stream)
        return Broker(coord), coord, stream

    SQL = "SET useResultCache = true; SELECT city, SUM(v) FROM rt GROUP BY city ORDER BY city"

    def test_hit_then_invalidate_on_realtime_append(self, tmp_path):
        broker, coord, stream = self._realtime_cluster(tmp_path)
        t0 = 1_700_000_000_000
        stream.publish_many(
            [{"city": ["sf", "nyc"][i % 2], "v": i, "ts": t0 + i} for i in range(40)], partition=0
        )
        coord.run_realtime_consumption()

        r1 = broker.query(self.SQL)
        assert r1.stats.result_cache == "miss"
        r2 = broker.query(self.SQL)
        assert r2.stats.result_cache == "hit"
        assert [tuple(r) for r in r2.rows] == [tuple(r) for r in r1.rows]

        # realtime append changes the version token -> served fresh, not stale
        stream.publish_many([{"city": "sf", "v": 1000, "ts": t0 + 100}], partition=0)
        coord.run_realtime_consumption()
        r3 = broker.query(self.SQL)
        assert r3.stats.result_cache == "miss"
        sf = dict((r[0], r[1]) for r in r3.rows)["sf"]
        assert sf == dict((r[0], r[1]) for r in r1.rows)["sf"] + 1000

    def test_explicit_invalidation_and_default_off(self, tmp_path):
        broker, coord, stream = self._realtime_cluster(tmp_path)
        stream.publish_many(
            [{"city": "sf", "v": 1, "ts": 1_700_000_000_000}], partition=0
        )
        coord.run_realtime_consumption()
        broker.query(self.SQL)
        assert len(broker.result_cache) == 1
        assert broker.invalidate_results("rt") == 1
        assert broker.query(self.SQL).stats.result_cache == "miss"
        # without the option the cache is never consulted
        plain = broker.query("SELECT SUM(v) FROM rt")
        assert getattr(plain.stats, "result_cache", None) is None


class TestObservabilitySurfaces:
    def test_dist_trace_plan_span_records_shape_fp_and_cache_hit(self, env):
        eng, _, _ = env
        sql = "SELECT city, SUM(v) FROM t GROUP BY city ORDER BY city LIMIT 10"
        eng.query(sql)  # warm the cache
        traced = eng.query("SET trace = true; " + sql)
        plan_span = next(c for c in traced.stats.trace["children"] if c["name"] == "plan")
        assert len(plan_span["attrs"]["shapeFp"]) == 12
        assert plan_span["attrs"]["planCache"] == "hit"

    def test_broker_explain_analyze_and_slowlog_record_fingerprint(self, tmp_path):
        from pinot_tpu.cluster import Broker, Coordinator, ServerInstance
        from pinot_tpu.segment.builder import build_segment

        schema = Schema(
            "o",
            [FieldSpec("city", DataType.STRING), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)],
        )
        coord = Coordinator(replication=1)
        coord.register_server(ServerInstance("s0"))
        coord.add_table(schema)
        rng = np.random.default_rng(5)
        d = {"city": rng.choice(["sf", "nyc"], 300).astype(object), "v": rng.integers(0, 9, 300)}
        coord.add_segment("o", build_segment(schema, d, "seg0"))
        broker = Broker(coord)
        res = broker.query("EXPLAIN ANALYZE SELECT city, SUM(v) FROM o GROUP BY city")
        plan_rows = [r[0] for r in res.rows if r[0].startswith("TRACE(plan)")]
        assert plan_rows and "shapeFp=" in plan_rows[0] and "resultCache=" in plan_rows[0]
        broker.query("SET useResultCache = true; SELECT COUNT(*) FROM o")
        entry = broker.slow_queries.snapshot()[0]
        assert len(entry["shapeFingerprint"]) == 12
        assert entry["resultCache"] == "miss"


class TestLruCache:
    def test_entry_bound_evicts_lru(self):
        c = LruCache(max_entries=2)
        c.put("a", 1)
        c.put("b", 2)
        assert c.get("a") == 1  # refresh a -> b is now LRU
        c.put("c", 3)
        assert "b" not in c and c.get("a") == 1 and c.get("c") == 3

    def test_bytes_bound_and_oversize_never_admits(self):
        c = LruCache(max_bytes=100, sizeof=lambda v: v)
        c.put("big", 101)
        assert "big" not in c
        c.put("a", 60)
        c.put("b", 60)  # evicts a
        assert "a" not in c and "b" in c and c.bytes == 60

    def test_ttl_expiry_with_injected_clock(self):
        c = LruCache(max_entries=8, ttl_s=10.0)
        now = [100.0]
        c.clock = lambda: now[0]
        c.put("k", "v")
        assert c.get("k") == "v"
        now[0] = 111.0
        assert c.get("k") is None and len(c) == 0

    def test_concurrent_get_put(self):
        c = LruCache(max_entries=32)
        errors = []

        def hammer(tid):
            try:
                for i in range(500):
                    c.put((tid, i % 50), i)
                    c.get((tid, (i * 7) % 50))
                    if i % 100 == 0:
                        c.invalidate_where(lambda k: k[0] == tid and k[1] % 13 == 0)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=hammer, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors and len(c) <= 32
