"""Broker query quota + adaptive replica selection + new minion tasks
(VERDICT r4 missing #9/#10, weak #11).

Reference model: HelixExternalViewBasedQueryQuotaManager (per-table QPS),
pinot-broker adaptiveserverselector (latency/in-flight biased routing),
UpsertCompactionTaskExecutor, RefreshSegmentTaskExecutor.
"""
import numpy as np
import pytest

from pinot_tpu.cluster import Broker, Coordinator, ServerInstance
from pinot_tpu.cluster.broker import AdaptiveServerStats, QueryQuotaManager, QuotaExceededError
from pinot_tpu.cluster.minion import MinionTaskManager
from pinot_tpu.realtime import InMemoryStream, RealtimeTableDataManager
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import (
    IndexingConfig,
    SegmentsConfig,
    StreamConfig,
    TableConfig,
    UpsertConfig,
)
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _data(n, seed=1):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc"], n).astype(object),
        "v": rng.integers(0, 100, n),
        "ts": 1_700_000_000_000 + rng.integers(0, 1000, n).astype(np.int64),
    }


class TestQueryQuota:
    def test_quota_token_bucket(self):
        q = QueryQuotaManager()
        for i in range(3):
            q.check("t", 3.0, now=100.0 + i * 0.001)  # burst capacity = qps
        with pytest.raises(QuotaExceededError):
            q.check("t", 3.0, now=100.01)
        # tokens refill at 3/s: ~0.4s later one query fits again
        q.check("t", 3.0, now=100.5)

    def test_broker_enforces_table_quota(self):
        coord = Coordinator(replication=1)
        coord.register_server(ServerInstance("s0"))
        cfg = TableConfig(
            name="t", segments=SegmentsConfig(time_column="ts"), max_queries_per_second=2.0
        )
        coord.add_table(_schema(), cfg)
        coord.add_segment("t", build_segment(_schema(), _data(100), "s", table_config=cfg))
        broker = Broker(coord)
        # frozen clock: query duration (JAX compiles!) must not refill tokens
        broker.quota.clock = lambda: 1000.0
        broker.query("SELECT COUNT(*) FROM t")
        broker.query("SELECT COUNT(*) FROM t")
        with pytest.raises(QuotaExceededError):
            broker.query("SELECT COUNT(*) FROM t")
        # advancing the clock refills
        broker.quota.clock = lambda: 1000.6
        broker.query("SELECT COUNT(*) FROM t")

    def test_quota_charges_once_per_request(self):
        """Set-op operands / subqueries must not double-charge the quota
        (review-caught: UNION ALL on a qps=1 table could never succeed)."""
        coord = Coordinator(replication=1)
        coord.register_server(ServerInstance("s0"))
        cfg = TableConfig(
            name="t", segments=SegmentsConfig(time_column="ts"), max_queries_per_second=1.0
        )
        coord.add_table(_schema(), cfg)
        coord.add_segment("t", build_segment(_schema(), _data(100), "s", table_config=cfg))
        broker = Broker(coord)
        broker.quota.clock = lambda: 50.0
        r = broker.query(
            "SELECT COUNT(*) FROM t UNION ALL SELECT COUNT(*) FROM t"
        )
        assert len(r.rows) == 2  # one request, one token

    def test_zero_quota_is_unlimited(self):
        q = QueryQuotaManager()
        for i in range(100):
            q.check("t", 0.0, now=50.0)

    def test_fractional_quota(self):
        """q=0.5 means one query per 2 seconds (review-caught: a 1s sliding
        window admitted ceil(q))."""
        q = QueryQuotaManager()
        q.check("t", 0.5, now=100.0)
        with pytest.raises(QuotaExceededError):
            q.check("t", 0.5, now=101.0)  # only 1s elapsed: 0.5 tokens
        q.check("t", 0.5, now=102.1)  # 2.1s since success: ~1.05 tokens

    def test_fractional_quota_refill_via_injectable_clock(self):
        """Same contract through the clock the broker path uses (no `now=`):
        q=0.5 admits exactly one query per 2-second window."""
        clk = [100.0]
        q = QueryQuotaManager()
        q.clock = lambda: clk[0]
        q.check("t", 0.5)
        admitted = 1
        for _ in range(40):  # walk 10s in 0.25s steps
            clk[0] += 0.25
            try:
                q.check("t", 0.5)
                admitted += 1
            except QuotaExceededError:
                pass
        assert admitted == 1 + 5  # one per 2s over the 10s walk


class TestAdaptiveSelection:
    def test_scores_prefer_fast_idle_servers(self):
        st = AdaptiveServerStats()
        st.begin("slow"); st.end("slow", 100.0)
        st.begin("fast"); st.end("fast", 5.0)
        assert st.score("fast") < st.score("slow")
        # in-flight load inflates the score
        st.begin("fast")
        st.begin("fast")
        assert st.score("fast") == 5.0 * 3

    def test_adaptive_routing_avoids_slow_replica(self):
        coord = Coordinator(replication=2)
        for i in range(2):
            coord.register_server(ServerInstance(f"server{i}"))
        cfg = TableConfig(name="t", segments=SegmentsConfig(time_column="ts"))
        coord.add_table(_schema(), cfg)
        for i in range(4):
            coord.add_segment("t", build_segment(_schema(), _data(50, seed=i), f"s{i}", table_config=cfg))
        broker = Broker(coord, selector="adaptive")
        # feed stats: server0 is 100x slower
        broker.server_stats.end("server0", 0)  # init entries
        broker.server_stats.ewma_ms["server0"] = 500.0
        broker.server_stats.ewma_ms["server1"] = 2.0
        assign = broker._route("t", [f"s{i}" for i in range(4)])
        # every segment replicated on both servers -> all go to the fast one
        assert set(assign) == {"server1"}
        # queries still work end-to-end and refresh the stats
        r = broker.query("SELECT COUNT(*) FROM t")
        assert int(r.rows[0][0]) == 200
        assert broker.server_stats.ewma_ms["server1"] != 2.0  # updated


class TestAdaptiveStatsConcurrency:
    def test_begin_end_under_concurrent_threads(self):
        """begin/end are read-modify-writes: unlocked, two begins could both
        read in_flight=0 (count lost -> later end drives it negative) and
        EWMA decay updates could vanish.  Hammer one shared server from many
        threads and verify the invariants hold."""
        import threading

        st = AdaptiveServerStats()
        n_threads, n_iter = 8, 500
        errors = []

        def worker(tid):
            try:
                for i in range(n_iter):
                    st.begin("shared")
                    st.end("shared", float((tid * n_iter + i) % 37) + 1.0)
                    # per-thread server: its EWMA entry must never be lost
                    st.begin(f"srv{tid}")
                    st.end(f"srv{tid}", 10.0 * (tid + 1))
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        # every begin was paired with an end: in-flight settles at exactly 0
        assert st.in_flight["shared"] == 0
        assert all(st.in_flight[f"srv{t}"] == 0 for t in range(n_threads))
        # no lost dict updates: every per-thread server kept its EWMA (each
        # thread always reports the same latency, so EWMA == that latency)
        for t in range(n_threads):
            assert st.ewma_ms[f"srv{t}"] == pytest.approx(10.0 * (t + 1))
        assert st.ewma_ms["shared"] > 0.0

    def test_punish_inflates_score(self):
        st = AdaptiveServerStats()
        st.begin("s"); st.end("s", 4.0)
        before = st.score("s")
        st.punish("s")
        assert st.score("s") >= max(2 * before, 50.0)
        # repeated punishment keeps compounding (flaky stays deprioritized)
        st.punish("s")
        assert st.ewma_ms["s"] == pytest.approx(100.0)


class TestUpsertCompaction:
    def test_compaction_drops_invalidated_rows(self, tmp_path):
        schema = Schema(
            "o",
            [
                FieldSpec("oid", DataType.STRING),
                FieldSpec("amount", DataType.DOUBLE, role=FieldRole.METRIC),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
            primary_key_columns=["oid"],
        )
        cfg = TableConfig(
            "o",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=10),
            upsert=UpsertConfig(mode="FULL", comparison_column="ts"),
        )
        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(schema, cfg, str(tmp_path / "t"), stream=stream)
        # 30 rows over 5 keys: each key updated 6x -> sealed segments carry
        # mostly-invalidated rows
        rows = [
            {"oid": f"k{i % 5}", "amount": float(i), "ts": 1000 + i} for i in range(30)
        ]
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        sealed_before = [s for segs in mgr.sealed.values() for s in segs]
        assert sealed_before and any(
            s.valid_docs is not None and not np.asarray(s.valid_docs).all() for s in sealed_before
        )
        from pinot_tpu.query.engine import QueryEngine

        eng = QueryEngine()
        eng.register_table(schema, cfg)
        eng.attach_realtime("o", mgr)
        before = eng.query("SELECT oid, amount FROM o ORDER BY oid LIMIT 10").rows

        coord = Coordinator(replication=1)
        report = MinionTaskManager(coord).upsert_compact("o", realtime_manager=mgr)
        assert report["compacted"] and report["rowsDropped"] > 0
        for segs in mgr.sealed.values():
            for s in segs:
                assert np.asarray(s.valid_docs).all()  # fully compacted
        after = eng.query("SELECT oid, amount FROM o ORDER BY oid LIMIT 10").rows
        assert before == after
        # further upserts still resolve correctly against remapped locations
        stream.publish({"oid": "k0", "amount": 999.0, "ts": 99999}, partition=0)
        mgr.consume_all()
        r = eng.query("SELECT amount FROM o WHERE oid = 'k0' LIMIT 2")
        assert len(r.rows) == 1 and float(r.rows[0][0]) == 999.0


class TestUpsertCompactionTombstones:
    def test_compaction_with_delete_tombstones(self, tmp_path):
        """A compacted-away tombstone row must not leave its pk_map location
        pointing into the shorter segment (review-caught: a later upsert
        would mask out a DIFFERENT key's live row)."""
        schema = Schema(
            "o",
            [
                FieldSpec("oid", DataType.STRING),
                FieldSpec("amount", DataType.DOUBLE, role=FieldRole.METRIC),
                FieldSpec("deleted", DataType.BOOLEAN),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
            primary_key_columns=["oid"],
        )
        cfg = TableConfig(
            "o",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=8),
            upsert=UpsertConfig(
                mode="FULL", comparison_column="ts", delete_record_column="deleted"
            ),
        )
        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(schema, cfg, str(tmp_path / "t"), stream=stream)
        rows = [
            {"oid": f"k{i % 4}", "amount": float(i), "deleted": False, "ts": 100 + i}
            for i in range(7)
        ]
        # tombstone k1 inside the first sealed segment (8 rows/seal)
        rows.append({"oid": "k1", "amount": 0.0, "deleted": True, "ts": 200})
        rows += [
            {"oid": f"k{i % 4}", "amount": 50.0 + i, "deleted": False, "ts": 300 + i}
            for i in range(4)
        ]
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        coord = Coordinator(replication=1)
        MinionTaskManager(coord).upsert_compact("o", realtime_manager=mgr, invalid_threshold=0.01)
        # tombstone entry is marked compacted-away, not a stale index
        assert mgr.upsert.pk_map[("k1",)].doc == -1 or not mgr.upsert.pk_map[("k1",)].deleted
        from pinot_tpu.query.engine import QueryEngine

        eng = QueryEngine()
        eng.register_table(schema, cfg)
        eng.attach_realtime("o", mgr)
        # a NEWER row revives k1; other keys keep exactly one live row each
        stream.publish({"oid": "k1", "amount": 77.0, "deleted": False, "ts": 999}, partition=0)
        mgr.consume_all()
        res = eng.query("SELECT oid, amount FROM o ORDER BY oid LIMIT 10")
        got = {a: float(b) for a, b in res.rows}
        # latest per key: k0 ts=300 amount=50, k1 revived at ts=999,
        # k2 ts=302 amount=52, k3 ts=303 amount=53
        assert got == {"k0": 50.0, "k1": 77.0, "k2": 52.0, "k3": 53.0}, got


class TestRefreshSegment:
    def test_refresh_picks_up_new_index_config(self):
        coord = Coordinator(replication=1)
        coord.register_server(ServerInstance("s0"))
        cfg = TableConfig(name="t", segments=SegmentsConfig(time_column="ts"))
        coord.add_table(_schema(), cfg)
        coord.add_segment("t", build_segment(_schema(), _data(500), "seg0", table_config=cfg))
        broker = Broker(coord)
        before = broker.query("SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city ORDER BY city").rows
        # config change: add an inverted index, then refresh
        meta = coord.tables["t"]
        meta.config.indexing = IndexingConfig(inverted_index_columns=["city"])
        report = MinionTaskManager(coord).run("RefreshSegmentTask", "t")
        assert report["refreshed"] == ["seg0"]
        after = broker.query("SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city ORDER BY city").rows
        assert before == after
        r = broker.query("SELECT COUNT(*) FROM t WHERE city = 'sf'")
        assert ("city", "inverted") in r.stats.filter_index_uses
