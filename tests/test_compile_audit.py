"""Recompilation guard (pinot_tpu.analysis.compile_audit): repeated
identical queries must hit the plan cache — the compile counter stays flat
while the hit counter climbs; a storming fingerprint warns (or raises in
strict mode)."""
import warnings

import numpy as np
import pytest

from pinot_tpu.analysis.compile_audit import (
    SSE_AUDIT,
    CompileAudit,
    RecompilationStormError,
)
from pinot_tpu.query import planner
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.utils.metrics import METRICS


def _counter(name):
    return METRICS.snapshot()["counters"].get(name, 0)


@pytest.fixture()
def eng():
    rng = np.random.default_rng(3)
    schema = Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.INT, role=FieldRole.METRIC),
        ],
    )
    e = QueryEngine()
    e.register_table(schema)
    data = {
        "city": rng.choice(["sf", "nyc"], 1000).astype(object),
        "v": rng.integers(0, 100, 1000).astype(np.int32),
    }
    e.add_segment("t", build_segment(schema, data, "s0"))
    return e


def test_repeated_query_compiles_once(eng):
    planner.plan_cache_clear()
    SSE_AUDIT.reset()
    METRICS.reset()
    sql = "SELECT city, SUM(v) FROM t GROUP BY city"
    eng.sql(sql)
    compiles_after_first = _counter("compile.sse.compiles")
    assert compiles_after_first >= 1
    for _ in range(5):
        eng.sql(sql)
    assert _counter("compile.sse.compiles") == compiles_after_first
    assert _counter("compile.sse.hits") >= 5
    # per-fingerprint view agrees: every fingerprint compiled exactly once
    assert all(n == 1 for n in SSE_AUDIT.counts().values())


def test_distinct_shapes_compile_separately(eng):
    planner.plan_cache_clear()
    SSE_AUDIT.reset()
    METRICS.reset()
    eng.sql("SELECT COUNT(*) FROM t")
    n1 = _counter("compile.sse.compiles")
    eng.sql("SELECT SUM(v) FROM t")
    n2 = _counter("compile.sse.compiles")
    assert n2 > n1


def test_storm_warns_then_raises_in_strict_mode():
    audit = CompileAudit("fixture", threshold=3, strict=False)
    for _ in range(3):
        audit.record_compile("fp")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        audit.record_compile("fp")
    assert any("recompilation storm" in str(x.message) for x in w)
    assert _counter("compile.fixture.storms") >= 1

    strict = CompileAudit("fixture2", threshold=1, strict=True)
    strict.record_compile("fp")
    with pytest.raises(RecompilationStormError):
        strict.record_compile("fp")


def test_reset_clears_counts():
    audit = CompileAudit("fixture3", threshold=10)
    audit.record_compile("a")
    assert audit.compile_count("a") == 1
    audit.reset()
    assert audit.compile_count("a") == 0 and audit.counts() == {}
