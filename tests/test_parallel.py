"""M2 tests: distributed shard_map engine over the 8-device CPU mesh,
golden-checked against sqlite3 and cross-checked against the in-process SSE
engine (same data via from_segments)."""
import numpy as np
import pytest

from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data

N = 6000
CITIES = ["sf", "nyc", "chi", "la", "sea", "pdx"]


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("year", DataType.INT),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("price", DataType.DOUBLE, role=FieldRole.METRIC, nullable=True),
        ],
    )


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(11)
    data = {
        "city": rng.choice(CITIES, N).astype(object),
        "year": rng.integers(2000, 2012, N).astype(np.int32),
        "v": rng.integers(-100, 1000, N),
        "price": np.where(rng.random(N) < 0.2, np.nan, np.round(rng.random(N) * 50, 3)),
    }
    st = StackedTable.build(_schema(), data, 8)
    eng = DistributedEngine()
    eng.register_table("t", st)
    conn = sqlite_from_data("t", data)
    return eng, conn, data


QUERIES = [
    "SELECT COUNT(*) FROM t",
    "SELECT COUNT(*), SUM(v), MIN(v), MAX(v), AVG(v) FROM t WHERE year >= 2006",
    "SELECT SUM(price), COUNT(price) FROM t",  # nulls
    "SELECT city, SUM(v) FROM t WHERE year BETWEEN 2003 AND 2009 GROUP BY city ORDER BY city LIMIT 20",
    "SELECT city, year, COUNT(*), AVG(price) FROM t GROUP BY city, year ORDER BY city, year LIMIT 200",
    "SELECT SUM(v) FROM t WHERE city IN ('sf', 'nyc') AND NOT year = 2004",
    "SELECT city, year FROM t WHERE v < -90 ORDER BY city, year LIMIT 12",
    "SELECT year, MIN(price), MAX(price) FROM t WHERE city = 'sf' GROUP BY year ORDER BY year LIMIT 20",
    "SELECT city, SUM(v) FROM t GROUP BY city HAVING SUM(v) > 100000 ORDER BY city LIMIT 10",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_distributed_vs_sqlite(env, sql):
    eng, conn, _ = env
    got = eng.query(sql)
    exp = conn.execute(sql).fetchall()
    assert_same_rows(got.rows, exp, ordered="ORDER BY" in sql)


def test_from_segments_matches_build(env):
    """Stacking pre-built heterogeneous segments re-aligns dictionaries."""
    eng, conn, data = env
    schema = _schema()
    sse = QueryEngine()
    sse.register_table(schema, TableConfig("t"))
    # two segments with different value subsets -> different dictionaries
    half = N // 2
    seg_data = [
        {k: np.asarray(v[:half]) for k, v in data.items()},
        {k: np.asarray(v[half:]) for k, v in data.items()},
    ]
    segs = [build_segment(schema, d, f"s{i}") for i, d in enumerate(seg_data)]
    st2 = StackedTable.from_segments(segs, num_shards=8)
    eng2 = DistributedEngine()
    eng2.register_table("t", st2)
    for sql in QUERIES[:5]:
        got = eng2.query(sql)
        exp = conn.execute(sql).fetchall()
        assert_same_rows(got.rows, exp, ordered="ORDER BY" in sql)


def test_sparse_groupby_path(env):
    """Force the sparse (host-finish) path via maxDenseGroups option."""
    eng, conn, _ = env
    sql = "SET maxDenseGroups = 2; SELECT city, year, COUNT(*) FROM t GROUP BY city, year ORDER BY city, year LIMIT 200"
    got = eng.query(sql)
    exp = conn.execute(
        "SELECT city, year, COUNT(*) FROM t GROUP BY city, year ORDER BY city, year LIMIT 200"
    ).fetchall()
    assert_same_rows(got.rows, exp, ordered=True)


def test_plan_cache(env):
    eng, _, _ = env
    n0 = len(eng._plan_cache)
    eng.query("SELECT SUM(v) FROM t WHERE year > 2001")
    n1 = len(eng._plan_cache)
    eng.query("SELECT SUM(v) FROM t WHERE year > 2007")  # same shape, new literal
    assert len(eng._plan_cache) >= n1  # distinct fingerprints may add entries
