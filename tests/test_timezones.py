"""Timezone-aware datetime functions (VERDICT r4 missing #7).

Reference model: DateTimeFunctions.java tz-suffixed variants (hour(millis,
tz), dateTrunc(unit, millis, unit, tz), toDateTime/fromDateTime with zone).
Golden model: stdlib zoneinfo per-row conversion.
"""
import datetime as dt
from zoneinfo import ZoneInfo

import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

NY = "America/New_York"
TOKYO = "Asia/Tokyo"


@pytest.fixture(scope="module")
def eng_ts():
    rng = np.random.default_rng(13)
    # spread across 4 years incl. DST transitions both ways
    base = int(dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc).timestamp() * 1000)
    ts = base + rng.integers(0, 4 * 365 * 24 * 3600 * 1000, 5000, dtype=np.int64)
    schema = Schema(
        "t",
        [
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            FieldSpec("v", DataType.INT, role=FieldRole.METRIC),
        ],
    )
    eng = QueryEngine()
    eng.register_table(schema)
    eng.add_segment("t", build_segment(schema, {"ts": ts, "v": np.ones(5000, np.int32)}, "s0"))
    return eng, ts


def _golden_part(ts, tz, part):
    z = ZoneInfo(tz)
    out = []
    for v in ts:
        d = dt.datetime.fromtimestamp(int(v) / 1000, tz=z)
        out.append(getattr(d, part))
    return np.asarray(out)


class TestTzExtract:
    @pytest.mark.parametrize("tz", [NY, TOKYO])
    def test_hour_counts(self, eng_ts, tz):
        eng, ts = eng_ts
        res = eng.query(f"SELECT HOUR(ts, '{tz}'), COUNT(*) FROM t GROUP BY HOUR(ts, '{tz}') ORDER BY HOUR(ts, '{tz}') LIMIT 30")
        want = np.bincount(_golden_part(ts, tz, "hour"), minlength=24)
        got = {int(h): int(c) for h, c in res.rows}
        for h in range(24):
            assert got.get(h, 0) == want[h], (h, got.get(h, 0), want[h])

    def test_day_month_year(self, eng_ts):
        eng, ts = eng_ts
        for part, attr in (("DAY", "day"), ("MONTH", "month"), ("YEAR", "year")):
            res = eng.query(
                f"SELECT {part}(ts, '{NY}'), COUNT(*) FROM t GROUP BY {part}(ts, '{NY}') "
                f"ORDER BY {part}(ts, '{NY}') LIMIT 40"
            )
            w = _golden_part(ts, NY, attr)
            uniq, counts = np.unique(w, return_counts=True)
            got = {int(a): int(b) for a, b in res.rows}
            for u, c in zip(uniq, counts):
                assert got[int(u)] == int(c)

    def test_utc_alias_matches_plain(self, eng_ts):
        eng, _ = eng_ts
        a = eng.query("SELECT HOUR(ts), COUNT(*) FROM t GROUP BY HOUR(ts) ORDER BY HOUR(ts) LIMIT 30").rows
        b = eng.query("SELECT HOUR(ts, 'UTC'), COUNT(*) FROM t GROUP BY HOUR(ts, 'UTC') ORDER BY HOUR(ts, 'UTC') LIMIT 30").rows
        assert a == b


class TestTzTrunc:
    def test_datetrunc_day_local_differs_from_utc(self, eng_ts):
        import jax.numpy as jnp

        from pinot_tpu.query import scalar

        _, ts = eng_ts
        local = np.asarray(scalar.DEVICE_FNS["datetrunc"](jnp.asarray(ts), "day", NY))
        utc = np.asarray(scalar.DEVICE_FNS["datetrunc"](jnp.asarray(ts), "day"))
        # NY local midnight is a different instant from UTC midnight
        # (offset -4/-5h) for every row
        assert np.all(local != utc)

    def test_datetrunc_matches_zoneinfo(self, eng_ts):
        """DATETRUNC('day', ts, tz) equals the zoneinfo local-midnight
        instant except within bucket-straddling DST shifts (excluded)."""
        eng, ts = eng_ts
        z = ZoneInfo(NY)
        res = eng.query(
            f"SELECT ts, DATETRUNC('day', ts, '{NY}') FROM t ORDER BY ts LIMIT 300"
        )
        for raw, got in res.rows:
            d = dt.datetime.fromtimestamp(int(raw) / 1000, tz=z)
            local_mid = d.replace(hour=0, minute=0, second=0, microsecond=0)
            want = int(local_mid.timestamp() * 1000)
            if d.utcoffset() != local_mid.utcoffset():
                continue  # bucket straddles the DST shift (documented delta)
            assert int(got) == want, (raw, got, want)


class TestOutputUnit:
    def test_five_arg_datetrunc_groupby(self, eng_ts):
        """5-arg form: result in outputTimeUnit; GROUP BY decode must match
        (review-caught: expr_int_range returned a millis range against
        seconds values)."""
        eng, ts = eng_ts
        res = eng.query(
            "SELECT DATETRUNC('year', ts, 'MILLISECONDS', 'SECONDS'), COUNT(*) FROM t "
            "GROUP BY DATETRUNC('year', ts, 'MILLISECONDS', 'SECONDS') "
            "ORDER BY DATETRUNC('year', ts, 'MILLISECONDS', 'SECONDS') LIMIT 10"
        )
        want = {}
        for v in ts:
            y = dt.datetime.fromtimestamp(int(v) / 1000, tz=dt.timezone.utc).year
            k = int(dt.datetime(y, 1, 1, tzinfo=dt.timezone.utc).timestamp())  # seconds
            want[k] = want.get(k, 0) + 1
        got = {int(a): int(b) for a, b in res.rows}
        assert got == want


class TestTzStrings:
    def test_todatetime_tz(self):
        from pinot_tpu.query import scalar

        ms = np.asarray([int(dt.datetime(2024, 7, 4, 3, 30, tzinfo=dt.timezone.utc).timestamp() * 1000)])
        out = scalar.to_datetime(ms, "yyyy-MM-dd HH:mm", NY)
        assert out[0] == "2024-07-03 23:30"  # EDT = UTC-4

    def test_fromdatetime_tz_roundtrip(self):
        from pinot_tpu.query.scalar import DICT_FNS

        vals = np.asarray(["2024-07-03 23:30", "2024-01-15 08:00"], dtype=object)
        got = DICT_FNS["fromdatetime"](vals, "yyyy-MM-dd HH:mm", NY)
        z = ZoneInfo(NY)
        want = [
            int(dt.datetime(2024, 7, 3, 23, 30, tzinfo=z).timestamp() * 1000),
            int(dt.datetime(2024, 1, 15, 8, 0, tzinfo=z).timestamp() * 1000),
        ]
        assert got.tolist() == want

    def test_unknown_zone_raises(self, eng_ts):
        eng, _ = eng_ts
        with pytest.raises(ValueError):
            eng.query("SELECT HOUR(ts, 'Not/AZone'), COUNT(*) FROM t GROUP BY HOUR(ts, 'Not/AZone') LIMIT 5")


def test_tz_ahead_of_utc_year_trunc(eng_ts):
    """Zones ahead of UTC can truncate one bucket ABOVE the UTC truncation
    (review-caught: Pacific/Auckland year boundary produced garbage keys)."""
    import numpy as np

    from pinot_tpu.query.engine import QueryEngine
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

    base = int(dt.datetime(2023, 12, 31, 22, 0, tzinfo=dt.timezone.utc).timestamp() * 1000)
    ts = base + np.arange(10, dtype=np.int64) * 60_000
    schema = Schema("a", [FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME)])
    eng = QueryEngine()
    eng.register_table(schema)
    eng.add_segment("a", build_segment(schema, {"ts": ts}, "s0"))
    res = eng.query(
        "SELECT DATETRUNC('year', ts, 'MILLISECONDS', 'Pacific/Auckland'), COUNT(*) FROM a "
        "GROUP BY DATETRUNC('year', ts, 'MILLISECONDS', 'Pacific/Auckland') LIMIT 5"
    )
    z = ZoneInfo("Pacific/Auckland")
    # all rows are local 2024 (UTC+13): bucket = 2024-01-01 local midnight
    want = int(dt.datetime(2024, 1, 1, tzinfo=z).timestamp() * 1000)
    assert [(int(a), int(b)) for a, b in res.rows] == [(want, 10)]
