"""M4 tests: SQL parser -> QueryContext IR, and SQL end-to-end through the
engine golden-checked against sqlite3 (the BaseQueriesTest+H2 tier shape)."""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.query.ir import (
    AggregationSpec,
    Expr,
    FilterOp,
    PredicateType,
)
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import SqlParseError, parse_query

from golden import assert_same_rows, sqlite_from_data


# ---------------------------------------------------------------------------
# IR-level
# ---------------------------------------------------------------------------
def test_parse_simple_agg():
    ctx = parse_query("SELECT COUNT(*), SUM(v) FROM t WHERE year > 2000")
    assert ctx.table == "t"
    assert ctx.select_list[0] == AggregationSpec("count", None)
    assert ctx.select_list[1] == AggregationSpec("sum", Expr.col("v"))
    p = ctx.filter.predicate
    assert p.ptype is PredicateType.RANGE and p.lower == 2000 and not p.lower_inclusive


def test_parse_groupby_having_orderby():
    ctx = parse_query(
        "SELECT city, SUM(v) AS total FROM t GROUP BY city "
        "HAVING SUM(v) > 100 ORDER BY total DESC, city LIMIT 5 OFFSET 2"
    )
    assert ctx.group_by == [Expr.col("city")]
    assert ctx.select_aliases == [None, "total"]
    assert ctx.having.predicate.lhs == Expr.call("sum", Expr.col("v"))
    assert ctx.order_by[0].ascending is False
    assert ctx.order_by[1].ascending is True
    assert ctx.limit == 5 and ctx.offset == 2


def test_parse_boolean_algebra():
    ctx = parse_query(
        "SELECT * FROM t WHERE (city = 'sf' OR city = 'nyc') AND NOT year IN (2001, 2002)"
    )
    f = ctx.filter
    assert f.op is FilterOp.AND
    assert f.children[0].op is FilterOp.OR
    assert f.children[1].op is FilterOp.NOT
    assert f.children[1].children[0].predicate.ptype is PredicateType.IN


def test_parse_between_like_null():
    ctx = parse_query(
        "SELECT v FROM t WHERE year BETWEEN 2001 AND 2003 AND city LIKE 's%' AND price IS NOT NULL"
    )
    kids = ctx.filter.children
    assert kids[0].predicate.ptype is PredicateType.RANGE
    assert kids[0].predicate.lower == 2001 and kids[0].predicate.upper == 2003
    assert kids[1].predicate.ptype is PredicateType.LIKE
    assert kids[2].predicate.ptype is PredicateType.IS_NOT_NULL


def test_parse_options_and_literals():
    ctx = parse_query("SET numGroupsLimit = 1000; SELECT COUNT(*) FROM t LIMIT 3")
    assert ctx.options["numGroupsLimit"] == 1000
    assert ctx.limit == 3
    ctx2 = parse_query("SELECT COUNT(*) FROM t OPTION(timeoutMs=500)")
    assert ctx2.options["timeoutMs"] == 500


def test_parse_arith_and_filtered_agg():
    ctx = parse_query(
        "SELECT SUM(v + 1) FILTER (WHERE city = 'sf'), AVG(v * 2) FROM t"
    )
    s0 = ctx.select_list[0]
    assert s0.function == "sum" and s0.filter is not None
    assert s0.expr == Expr.call("plus", Expr.col("v"), Expr.lit(1))
    assert ctx.select_list[1].expr == Expr.call("times", Expr.col("v"), Expr.lit(2))


def test_parse_constant_fold():
    ctx = parse_query("SELECT COUNT(*) FROM t WHERE v > 10 * 2 + 5")
    assert ctx.filter.predicate.lower == 25


def test_parse_errors():
    with pytest.raises(SqlParseError):
        parse_query("SELECT FROM t")
    with pytest.raises(SqlParseError):
        parse_query("SELECT * t")
    with pytest.raises(SqlParseError):
        parse_query("SELECT * FROM t WHERE")


# ---------------------------------------------------------------------------
# End-to-end SQL vs sqlite
# ---------------------------------------------------------------------------
N = 4000
CITIES = ["sf", "nyc", "chi", "la", "sea"]


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(7)
    schema = Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("year", DataType.INT),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    engine = QueryEngine()
    engine.register_table(schema, TableConfig("t"))
    all_data = {k: [] for k in ("city", "year", "v")}
    for seed in (1, 2):
        data = {
            "city": rng.choice(CITIES, N).astype(object),
            "year": rng.integers(2000, 2010, N).astype(np.int32),
            "v": rng.integers(0, 1000, N),
        }
        seg = build_segment(schema, data, f"s{seed}")
        engine.add_segment("t", seg)
        for k in all_data:
            all_data[k].append(data[k])
    merged = {k: np.concatenate(v) for k, v in all_data.items()}
    conn = sqlite_from_data("t", merged)
    return engine, conn


QUERIES = [
    "SELECT COUNT(*) FROM t",
    "SELECT COUNT(*), SUM(v), MIN(v), MAX(v), AVG(v) FROM t WHERE year >= 2005",
    "SELECT city, SUM(v) FROM t WHERE year BETWEEN 2002 AND 2008 GROUP BY city ORDER BY city LIMIT 20",
    "SELECT city, year, COUNT(*) FROM t GROUP BY city, year HAVING COUNT(*) > 50 ORDER BY city, year LIMIT 100",
    "SELECT SUM(v) FROM t WHERE city IN ('sf', 'nyc') AND NOT year = 2003",
    "SELECT city FROM t WHERE v < 5 ORDER BY city LIMIT 10",
    "SELECT year, AVG(v) FROM t WHERE city = 'sf' OR city = 'la' GROUP BY year ORDER BY year LIMIT 20",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_sql_end_to_end(env, sql):
    engine, conn = env
    got = engine.query(sql)
    exp = conn.execute(sql.replace("FILTER (WHERE", "FILTER (WHERE")).fetchall()
    ordered = "ORDER BY" in sql
    assert_same_rows(got.rows, exp, ordered=ordered)


def test_sql_distinct(env):
    engine, conn = env
    got = engine.query("SELECT DISTINCT city FROM t LIMIT 50")
    exp = conn.execute("SELECT DISTINCT city FROM t LIMIT 50").fetchall()
    assert_same_rows(got.rows, exp, ordered=False)
