"""Device-side high-cardinality (sparse) group-by tests.

The IndexedTable analog (reference: pinot-core/.../core/data/table/
IndexedTable.java:46,105-123) now runs on-device: sort + segment-scatter
into fixed numGroupsLimit-sized tables.  These tests pin:
  * correctness vs sqlite at key spaces past the dense-table bound
  * NO row-length array ever crosses device_get (the round-1/2 regression)
  * deterministic numGroupsLimit trim (lowest packed keys win)
  * the distributed path merges per-device tables by key
"""
import numpy as np
import pytest

import pinot_tpu.query.executor as executor_mod
from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.sql.parser import parse_query
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data

N = 60_000


def _schema():
    return Schema(
        "hc",
        [
            FieldSpec("k1", DataType.INT),
            FieldSpec("k2", DataType.INT),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("w", DataType.DOUBLE, role=FieldRole.METRIC),
        ],
    )


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(17)
    return {
        # 1500 x 1500 = 2.25M key space > maxDenseGroups (1M) -> sparse path
        "k1": rng.integers(0, 1500, N).astype(np.int32),
        "k2": rng.integers(0, 1500, N).astype(np.int32),
        "v": rng.integers(-50, 5000, N),
        "w": np.round(rng.random(N) * 100, 3),
    }


@pytest.fixture(scope="module")
def sse(data):
    eng = QueryEngine()
    eng.register_table(_schema())
    eng.add_segment("hc", build_segment(_schema(), data, "s0"))
    return eng


@pytest.fixture(scope="module")
def conn(data):
    return sqlite_from_data("hc", data)


SPARSE_SQL = "SELECT k1, k2, COUNT(*), SUM(v), MIN(v), MAX(w), AVG(w) FROM hc GROUP BY k1, k2"


class TestSparseGroupBy:
    def test_plan_kind_is_sparse(self, sse):
        from pinot_tpu.query import planner

        ctx = parse_query(SPARSE_SQL)
        seg = sse.table("hc").segments[0]
        plan = planner.plan_segment(ctx, seg)
        assert plan.kind == "groupby_sparse"

    def test_matches_sqlite(self, sse, conn):
        sql = SPARSE_SQL + " ORDER BY k1, k2 LIMIT 100"
        assert_same_rows(sse.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_filtered_sparse_matches_sqlite(self, sse, conn):
        sql = (
            "SELECT k1, k2, SUM(v), COUNT(*) FROM hc WHERE v > 2500 "
            "GROUP BY k1, k2 ORDER BY k1 DESC, k2 DESC LIMIT 50"
        )
        assert_same_rows(sse.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_no_row_length_device_transfer(self, sse, monkeypatch):
        """The kernel must return only table-sized arrays — the whole point
        of killing the host np.unique fallback."""
        import jax

        seen_sizes = []
        real_get = jax.device_get

        def spy(x):
            for leaf in jax.tree_util.tree_leaves(x):
                seen_sizes.append(int(np.asarray(leaf).size))
            return real_get(x)

        monkeypatch.setattr(jax, "device_get", spy)
        ctx = parse_query(SPARSE_SQL)
        ctx.options["numGroupsLimit"] = 5000  # tables are limit-sized, not row-sized
        sse.execute(ctx)
        assert seen_sizes, "device_get never called?"
        assert max(seen_sizes) <= 5000, f"array larger than the group table crossed PCIe: {max(seen_sizes)}"

    def test_num_groups_limit_trim_deterministic(self, sse):
        ctx = parse_query("SELECT k1, k2, COUNT(*) FROM hc GROUP BY k1, k2 LIMIT 100000")
        ctx.options["numGroupsLimit"] = 500
        res = sse.execute(ctx)
        assert len(res.rows) == 500
        # lowest packed (k1, k2) keys win the trim
        got = sorted((r[0], r[1]) for r in res.rows)
        assert got == sorted(got)[:500]
        ctx2 = parse_query("SELECT k1, k2, COUNT(*) FROM hc GROUP BY k1, k2 LIMIT 100000")
        ctx2.options["numGroupsLimit"] = 500
        res2 = sse.execute(ctx2)
        assert sorted(map(tuple, res.rows)) == sorted(map(tuple, res2.rows))


class TestOrderByAwareTrim:
    """TableResizer analog (pinot-core/.../core/data/table/TableResizer.java):
    when groups exceed numGroupsLimit and the query ORDERs BY an aggregate,
    the trim must keep the comparator's top groups, not the lowest packed
    keys (round-5 VERDICT #4)."""

    def _engine(self, data):
        eng = QueryEngine()
        eng.register_table(_schema())
        eng.add_segment("hc", build_segment(_schema(), data, "s0"))
        return eng

    @pytest.fixture(scope="class")
    def skewed(self):
        rng = np.random.default_rng(99)
        n = 30_000
        # keys 1200..1399 are "hot": huge v sums; packed-key trim would keep
        # the LOWEST keys and miss every one of them
        k = rng.integers(0, 1400, n).astype(np.int32)
        v = np.where(k >= 1200, 1_000_000 + k.astype(np.int64), rng.integers(1, 100, n))
        return {
            "k1": k,
            "k2": np.zeros(n, dtype=np.int32),
            "v": v.astype(np.int64),
            "w": rng.random(n),
        }

    @pytest.mark.parametrize(
        "agg,eng_order,sql_order",
        [
            ("SUM(v)", "SUM(v) DESC", "SUM(v) DESC"),
            ("COUNT(*)", "COUNT(*) DESC", "COUNT(*) DESC"),
            ("MAX(v)", "MAX(v) DESC", "MAX(v) DESC"),
            ("MIN(v)", "MIN(v) ASC", "MIN(v) ASC"),
            ("SUM(v)", "s DESC", "SUM(v) DESC"),  # alias resolution
        ],
    )
    def test_sparse_trim_keeps_true_top(self, skewed, agg, eng_order, sql_order):
        eng = self._engine(skewed)
        conn = sqlite_from_data("hc", skewed)
        sql = f"SELECT k1, {agg} AS s FROM hc GROUP BY k1 ORDER BY {sql_order}, k1 LIMIT 10"
        ctx = parse_query(
            f"SET maxDenseGroups = 2; SET numGroupsLimit = 50; "
            f"SELECT k1, {agg} AS s FROM hc GROUP BY k1 ORDER BY {eng_order}, k1 LIMIT 10"
        )
        got = eng.execute(ctx)
        exp = conn.execute(sql).fetchall()
        assert_same_rows(got.rows, exp, ordered=True)

    @pytest.mark.parametrize("order", ["MIN(w) DESC", "MAX(w) ASC", "SUM(w) DESC"])
    def test_null_group_ranks_last_in_kernel_trim(self, order):
        """A group whose order-agg values are all NULL must rank LAST in
        every direction (review-caught: the +inf sentinel flipped sign for
        MIN DESC / MAX ASC and evicted true top groups)."""
        rng = np.random.default_rng(7)
        n = 8_000
        k = rng.integers(0, 200, n).astype(np.int32)
        w = rng.random(n) * 100 + 1
        w[k == 0] = np.nan  # group 0: all NULL order values
        data = {"k1": k, "k2": np.zeros(n, np.int32), "v": np.ones(n, np.int64), "w": w}
        schema = Schema(
            "hc",
            [
                FieldSpec("k1", DataType.INT),
                FieldSpec("k2", DataType.INT),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("w", DataType.DOUBLE, role=FieldRole.METRIC, nullable=True),
            ],
        )
        eng = QueryEngine()
        eng.register_table(schema)
        eng.add_segment("hc", build_segment(schema, data, "s0"))
        sql = f"SELECT k1, COUNT(*) FROM hc GROUP BY k1 ORDER BY {order}, k1 LIMIT 5"
        ctx = parse_query("SET maxDenseGroups = 2; SET numGroupsLimit = 20; " + sql)
        got = eng.execute(ctx)
        # ground truth: the untrimmed dense path (engine NULLS-LAST default;
        # sqlite's NULLS-smallest convention differs on the ASC cases)
        exp = eng.query(sql)
        assert_same_rows(got.rows, exp.rows, ordered=True)

    def test_nan_order_value_does_not_poison_trim(self):
        """A computed-NaN order value (0/0 with the agg mask still TRUE) must
        rank its own group last WITHOUT poisoning the prefix sums of every
        later-keyed group (review-caught: one NaN in the cumsum dropped all
        groups sorting after it)."""
        rng = np.random.default_rng(21)
        n = 9_000
        k = rng.integers(0, 300, n).astype(np.int32)
        v = rng.integers(1, 50, n).astype(np.int64)
        w = rng.random(n) + 0.5
        nanrows = k == 5
        v[nanrows] = 0
        w[nanrows] = 0.0  # v / w = 0 / 0 -> NaN, agg mask true
        data = {"k1": k, "k2": np.zeros(n, np.int32), "v": v, "w": w}
        eng = self._engine(data)
        sql = "SELECT k1, SUM(v / w) AS s FROM hc GROUP BY k1 ORDER BY s DESC, k1 LIMIT 10"
        got = eng.execute(
            parse_query("SET maxDenseGroups = 2; SET numGroupsLimit = 40; " + sql)
        )
        exp = eng.query(sql)  # untrimmed dense path: ground truth
        assert_same_rows(got.rows, exp.rows, ordered=True)

    def test_dense_trim_keeps_true_top(self, skewed):
        """Dense-path numGroupsLimit trim ranks by the comparator too —
        including non-additive finals like AVG."""
        eng = self._engine(skewed)
        conn = sqlite_from_data("hc", skewed)
        sql = (
            "SELECT k1, AVG(v) FROM hc GROUP BY k1 "
            "ORDER BY AVG(v) DESC, k1 LIMIT 10"
        )
        ctx = parse_query("SET numGroupsLimit = 50; " + sql)
        got = eng.execute(ctx)
        exp = conn.execute(sql).fetchall()
        assert_same_rows(got.rows, exp, ordered=True)

    def test_distributed_sparse_trim(self, skewed):
        st = StackedTable.build(_schema(), skewed, 8)
        eng = DistributedEngine()
        eng.register_table("hc", st)
        conn = sqlite_from_data("hc", skewed)
        sql = (
            "SELECT k1, SUM(v) FROM hc GROUP BY k1 "
            "ORDER BY SUM(v) DESC, k1 LIMIT 10"
        )
        got = eng.query("SET maxDenseGroups = 2; SET numGroupsLimit = 300; " + sql)
        exp = conn.execute(sql).fetchall()
        assert_same_rows(got.rows, exp, ordered=True)


class TestDistributedSparse:
    @pytest.fixture(scope="class")
    def dist(self, data):
        st = StackedTable.build(_schema(), data, 8)
        eng = DistributedEngine()
        eng.register_table("hc", st)
        return eng

    def test_distributed_matches_sqlite(self, dist, conn):
        sql = SPARSE_SQL + " ORDER BY k1, k2 LIMIT 100"
        assert_same_rows(dist.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)

    def test_cross_device_key_merge(self, dist, conn):
        """Groups spanning shards must merge, not duplicate."""
        sql = "SELECT k1, COUNT(*), SUM(v) FROM hc GROUP BY k1 ORDER BY k1 LIMIT 2000"
        ctx = parse_query(sql)
        ctx.options["maxDenseGroups"] = 100  # force the sparse path at card 1500
        res = dist.execute(ctx)
        expected = conn.execute(sql).fetchall()
        assert_same_rows(res.rows, expected, ordered=True)


class TestFusedInChunkPath:
    def test_inchunk_limb_extraction_matches(self, monkeypatch):
        """Past _FUSED_STACK_BYTES the fused scan extracts limbs per chunk
        (no [n, L] HBM intermediate — the 1B-row OOM fix); results must be
        identical to the pre-stacked path."""
        import jax
        import jax.numpy as jnp

        from pinot_tpu.ops import segmented as seg

        rng = np.random.default_rng(2)
        n, G = 70_000, 300
        codes = jnp.asarray(rng.integers(0, G, n).astype(np.int32))
        vals = jnp.asarray(rng.integers(-500, 50_000, n).astype(np.int32))
        fvals = jnp.asarray(rng.random(n).astype(np.float32))
        mask = jnp.asarray(rng.random(n) < 0.7)
        lp = seg.sum_limb_plan(-500, 50_000)
        entries = [("count", None, mask, None), ("int_sum", vals, mask, lp), ("f32_sumsq", fvals, mask, None)]

        a = [np.asarray(t) for t in jax.jit(lambda c: seg.fused_group_tables(entries, c, G))(codes)]
        monkeypatch.setattr(seg, "_FUSED_STACK_BYTES", 1)
        b = [np.asarray(t) for t in jax.jit(lambda c: seg.fused_group_tables(entries, c, G))(codes)]
        for x, y in zip(a, b):
            assert np.allclose(x, y, rtol=1e-6, atol=1e-3)
        # int sums stay bit-exact on the in-chunk path
        exp = np.zeros(G, np.int64)
        np.add.at(exp, np.asarray(codes), np.where(np.asarray(mask), np.asarray(vals).astype(np.int64), 0))
        assert np.array_equal(b[1].astype(np.int64), exp)
