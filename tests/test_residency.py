"""Tiered segment storage (round 14, segment/residency.py).

HBM is a byte-budgeted cache over host RAM: these tests drive the residency
state machine (host-only -> staging -> resident -> evicting) under
concurrency, kill a stage mid-flight through the r12 crash harness and
assert the budget ledger never leaks, race queries against evictions to
prove a group's raw and #packed flavors drop atomically (a reader can
never observe half a segment), check the prefetch-hit accounting parity of
the engine's double-buffered staging stream, and pin the staged-fetch
admission semantics (ReservationError only when the working set cannot fit
even transiently).
"""
import itertools
import threading
import time

import numpy as np
import pytest

from pinot_tpu.cluster.admission import ReservationError, ResourceBudget
from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.residency import (
    EVICTING,
    HIT,
    HOST_ONLY,
    OWN,
    RESIDENT,
    STAGING,
    WAIT,
    ResidencyManager,
)
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.utils import crashpoints
from pinot_tpu.utils.metrics import METRICS
from pinot_tpu.utils.perf import PerfLedger

_seq = itertools.count()


def _mgr(budget_bytes, ledger=None):
    """Fresh manager with a unique metrics namespace (global registry)."""
    return ResidencyManager(
        ResourceBudget(budget_bytes), name=f"res.t{next(_seq)}", ledger=ledger
    )


def _segment(name="segres", n=4096):
    schema = Schema(
        name,
        [
            FieldSpec("g", DataType.INT),
            FieldSpec("v", DataType.INT, role=FieldRole.METRIC),
        ],
    )
    rng = np.random.default_rng(3)
    return build_segment(
        schema,
        {
            "g": rng.integers(0, 16, n).astype(np.int32),
            "v": rng.integers(0, 100, n).astype(np.int32),
        },
        "s0",
    )


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


class TestStateMachine:
    def test_own_stage_commit_hit_evict(self):
        res = _mgr(1_000)
        evicted = []
        g = ("seg", 1, None)
        st, e = res.begin_stage(g, "t", lambda: evicted.append(g))
        assert st == OWN and res.state_of(g) == STAGING
        res.charge(g, 400)
        res.finish_stage(g)
        assert res.state_of(g) == RESIDENT
        assert res.resident_bytes == 400 == res.budget.in_use
        st2, _ = res.begin_stage(g, "t", lambda: None)
        assert st2 == HIT
        assert res.evict(g)
        assert evicted == [g]
        assert res.state_of(g) == HOST_ONLY
        assert res.resident_bytes == 0 == res.budget.in_use

    def test_waiters_park_then_hit_after_commit(self):
        res = _mgr(1_000)
        g = ("seg", 2, None)
        st, _ = res.begin_stage(g, "t", lambda: None)
        assert st == OWN
        statuses = []

        def waiter():
            s, entry = res.begin_stage(g, "t", lambda: None)
            statuses.append(s)
            if s == WAIT:
                assert res.wait(entry, timeout_s=5.0)

        threads = [threading.Thread(target=waiter) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)  # let waiters park on the STAGING entry
        res.charge(g, 100)
        res.finish_stage(g)
        for t in threads:
            t.join()
        assert statuses == [WAIT] * 4
        assert res.state_of(g) == RESIDENT

    def test_abort_of_fresh_stage_removes_entry_and_uncharges(self):
        res = _mgr(1_000)
        g = ("seg", 3, None)
        res.begin_stage(g, "t", lambda: None)
        res.charge(g, 300)
        assert res.budget.in_use == 300
        res.abort_stage(g)
        assert res.state_of(g) == HOST_ONLY
        assert res.budget.in_use == 0 and res.resident_bytes == 0

    def test_abort_of_grow_reverts_to_resident(self):
        res = _mgr(1_000)
        g = ("seg", 4, None)
        res.begin_stage(g, "t", lambda: None)
        res.charge(g, 200)
        res.finish_stage(g)
        st, _ = res.begin_grow(g)
        assert st == OWN and res.state_of(g) == STAGING
        res.charge(g, 150)
        res.abort_stage(g)
        # the committed 200 bytes survive; only the grow's 150 unwind
        assert res.state_of(g) == RESIDENT
        assert res.resident_bytes == 200 == res.budget.in_use


# ---------------------------------------------------------------------------
# cost-aware eviction
# ---------------------------------------------------------------------------


class TestCostRankedEviction:
    def test_cold_table_evicted_before_hot_despite_recency(self):
        ledger = PerfLedger()
        # hot table: high bytes/s in the r13 ledger -> expensive to refetch
        ledger.record("hotT", "fp", rows=1e6, time_ms=10.0, kernel_bytes=1e9)
        res = _mgr(1_000, ledger=ledger)
        evicted = []
        a, b, c = ("a", None), ("b", None), ("c", None)
        res.begin_stage(a, "coldT", lambda: evicted.append("a"))
        res.charge(a, 400)
        res.finish_stage(a)
        res.begin_stage(b, "hotT", lambda: evicted.append("b"))
        res.charge(b, 400)
        res.finish_stage(b)
        res.touch(a)  # pure LRU would now pick b; the heat signal must win
        res.begin_stage(c, "t3", lambda: evicted.append("c"))
        res.charge(c, 400)
        res.finish_stage(c)
        assert evicted == ["a"]
        assert res.state_of(a) == HOST_ONLY and res.state_of(b) == RESIDENT

    def test_lru_fallback_without_ledger_signal(self):
        res = _mgr(1_000)
        evicted = []
        a, b, c = ("a", None), ("b", None), ("c", None)
        for g, nm in ((a, "a"), (b, "b")):
            res.begin_stage(g, "t", lambda nm=nm: evicted.append(nm))
            res.charge(g, 400)
            res.finish_stage(g)
        res.touch(a)  # b is now least-recent
        res.begin_stage(c, "t", lambda: evicted.append("c"))
        res.charge(c, 400)
        res.finish_stage(c)
        assert evicted == ["b"]

    def test_unfittable_charge_raises_and_unwinds(self):
        res = _mgr(100)
        g = ("seg", 9, None)
        res.begin_stage(g, "t", lambda: None)
        with pytest.raises(ReservationError):
            res.charge(g, 200)
        res.abort_stage(g)
        assert res.state_of(g) == HOST_ONLY
        assert res.budget.in_use == 0 and res.resident_bytes == 0


# ---------------------------------------------------------------------------
# mid-stage kill (r12 crash harness): no budget leak
# ---------------------------------------------------------------------------


class TestMidStageCrash:
    @pytest.fixture(autouse=True)
    def _clean_points(self):
        crashpoints.reset()
        yield
        crashpoints.reset()

    @pytest.mark.parametrize(
        "point", ["segment.stage.after_charge", "segment.stage.after_copy"]
    )
    def test_killed_stage_leaves_no_ledger_leak_and_retries_clean(self, point):
        seg = _segment()
        res = _mgr(10 << 20)
        crashpoints.arm(point)
        with pytest.raises(crashpoints.InjectedCrash):
            seg.to_device(residency=res)
        g = seg.device_group(None)
        assert res.state_of(g) == HOST_ONLY
        assert res.budget.in_use == 0 and res.resident_bytes == 0
        # the point disarmed on firing: the post-restart retry commits
        cols = seg.to_device(residency=res)
        assert set(cols) == set(seg.column_names)
        assert res.state_of(g) == RESIDENT
        assert res.budget.in_use == res.resident_bytes > 0

    def test_killed_grow_keeps_committed_bytes(self):
        seg = _segment()
        res = _mgr(10 << 20)
        seg.to_device(columns=["g"], residency=res)
        committed = res.resident_bytes
        assert committed > 0
        crashpoints.arm("segment.stage.after_copy")
        with pytest.raises(crashpoints.InjectedCrash):
            seg.to_device(columns=["g", "v"], residency=res)
        assert res.state_of(seg.device_group(None)) == RESIDENT
        assert res.budget.in_use == res.resident_bytes == committed
        cols = seg.to_device(columns=["g", "v"], residency=res)
        assert set(cols) == {"g", "v"}
        assert res.budget.in_use == res.resident_bytes > committed


# ---------------------------------------------------------------------------
# atomic flavor eviction: a reader never mixes tiers
# ---------------------------------------------------------------------------


class TestAtomicFlavorEviction:
    def test_concurrent_readers_race_eviction_without_mixing(self):
        """Readers alternate raw and #packed requests while an evictor
        drops the group; every assembled pytree must be complete for the
        requested flavor (assemble returns None on a half-evicted cache and
        the reader re-stages — satellite fix r17)."""
        seg = _segment(n=8192)
        res = _mgr(10 << 20)
        stop = threading.Event()
        errors = []

        def reader(packed):
            try:
                for _ in range(30):
                    cols = seg.to_device(packed_codes=packed, residency=res)
                    if set(cols) != set(seg.column_names):
                        errors.append(f"partial pytree: {sorted(cols)}")
            except Exception as exc:  # pragma: no cover - failure detail
                errors.append(repr(exc))

        def evictor():
            while not stop.is_set():
                res.evict(seg.device_group(None))
                time.sleep(0.001)

        threads = [threading.Thread(target=reader, args=(p,)) for p in (False, True)]
        ev = threading.Thread(target=evictor)
        ev.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        ev.join()
        assert errors == []
        # ledger is exact after the dust settles: committed == charged
        assert res.budget.in_use == res.resident_bytes

    def test_single_owner_stages_group_once(self):
        seg = _segment()
        res = _mgr(10 << 20)
        miss0 = METRICS.counter(f"{res.name}.misses").value
        barrier = threading.Barrier(6)
        outs = []
        lock = threading.Lock()

        def worker():
            barrier.wait()
            cols = seg.to_device(residency=res)
            with lock:
                outs.append(set(cols))

        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(o == set(seg.column_names) for o in outs)
        # one miss -> one staging owner; everyone else waited or hit
        assert METRICS.counter(f"{res.name}.misses").value - miss0 == 1


# ---------------------------------------------------------------------------
# staged-fetch admission (reserve_or_wait)
# ---------------------------------------------------------------------------


class TestStagedFetchAdmission:
    def test_rejects_immediately_when_unfittable_even_transiently(self):
        b = ResourceBudget(100)
        with pytest.raises(ReservationError, match="even\\s+transiently"):
            b.reserve_or_wait(150, max_wait_ms=5_000)

    def test_parks_until_release_then_admits(self):
        b = ResourceBudget(100)
        t = b.reserve(80)
        served0 = METRICS.counter("admission.stagedFetchServed").value

        def releaser():
            time.sleep(0.05)
            b.release(t)

        th = threading.Thread(target=releaser)
        th.start()
        ticket = b.reserve_or_wait(50, max_wait_ms=5_000)
        th.join()
        assert b.in_use == 50
        assert METRICS.counter("admission.stagedFetchServed").value == served0 + 1
        b.release(ticket)

    def test_times_out_to_out_of_capacity(self):
        b = ResourceBudget(100)
        b.reserve(80)
        t0 = METRICS.counter("admission.stagedFetchTimeouts").value
        with pytest.raises(ReservationError, match="staged wait"):
            b.reserve_or_wait(50, max_wait_ms=40)
        assert METRICS.counter("admission.stagedFetchTimeouts").value == t0 + 1


# ---------------------------------------------------------------------------
# engine integration: tiered vs pinned bit-exactness + prefetch accounting
# ---------------------------------------------------------------------------

N = 64 * 1024  # with launch_bytes=8000 the doc axis splits into ~5 batches


@pytest.fixture(scope="module")
def tiered_pair():
    schema = Schema(
        "t",
        [
            FieldSpec("d", DataType.INT),
            FieldSpec("v", DataType.INT, role=FieldRole.METRIC),
        ],
    )
    rng = np.random.default_rng(5)
    data = {
        "d": rng.integers(0, 64, N).astype(np.int32),
        "v": rng.integers(-50, 50, N).astype(np.int32),
    }

    def build(cache_bytes):
        eng = DistributedEngine(launch_bytes=8_000, hbm_cache_bytes=cache_bytes)
        eng.register_table("t", StackedTable.build(schema, dict(data), eng.num_devices))
        return eng

    # cache ~= 1/3 of the working set: every query cycles through eviction
    tiered, ref = build(128_000), build(0)
    yield tiered, ref
    tiered.residency.shutdown()


QUERIES = [
    "SELECT COUNT(*), SUM(v), MIN(v), MAX(v) FROM t",
    "SELECT COUNT(*), SUM(v) FROM t WHERE d < 32",
    "SELECT d, COUNT(*), SUM(v) FROM t GROUP BY d ORDER BY d LIMIT 70",
]


class TestTieredEngine:
    def test_over_budget_working_set_is_bit_exact(self, tiered_pair):
        tiered, ref = tiered_pair
        ev0 = METRICS.counter("residency.evictions").value
        for q in QUERIES:
            assert tiered.query(q).rows == ref.query(q).rows
        assert METRICS.counter("residency.evictions").value > ev0

    def test_queries_racing_manager_evictions_stay_exact(self, tiered_pair):
        tiered, ref = tiered_pair
        q = QUERIES[2]
        expect = ref.query(q).rows
        stop = threading.Event()

        def evictor():
            while not stop.is_set():
                tiered.residency.evict_matching(lambda g: True)
                time.sleep(0.002)

        th = threading.Thread(target=evictor)
        th.start()
        try:
            for _ in range(6):
                assert tiered.query(q).rows == expect
        finally:
            stop.set()
            th.join()

    def test_prefetch_hit_accounting_parity(self, tiered_pair):
        """Every streamed macro-batch is consumed exactly once as either a
        prefetch hit or a staging stall — identical reruns see identical
        hit+stall deltas (the sweep's hit-rate denominator is exact)."""
        tiered, _ = tiered_pair
        q = QUERIES[1]
        tiered.query(q)  # warm compile

        def delta():
            h0 = METRICS.counter("engine.prefetchHits").value
            s0 = METRICS.counter("engine.stagingStalls").value
            tiered.query(q)
            return (
                METRICS.counter("engine.prefetchHits").value - h0,
                METRICS.counter("engine.stagingStalls").value - s0,
            )

        h1, s1 = delta()
        h2, s2 = delta()
        assert h1 + s1 == h2 + s2 > 1
