"""Star-tree index: build, route, answer from collapsed levels, persist.

Every query runs twice — star-tree enabled vs SET useStarTree=false — and
both must match the sqlite golden answer; the star run must scan (far) fewer
docs and report the startree index use.  (StarTreeV2 / StarTreeFilterOperator
analog coverage, SURVEY.md section 2.1 row "Star-tree index".)"""
import numpy as np
import pytest

from tests.golden import assert_same_rows, sqlite_from_data

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

N = 8000
YEARS = list(range(1992, 1999))
REGIONS = ["AMERICA", "ASIA", "EUROPE", "AFRICA"]
CATS = ["c%d" % i for i in range(12)]


def _data(rng):
    return {
        "d_year": rng.choice(YEARS, N).astype(np.int32),
        "region": rng.choice(REGIONS, N).astype(object),
        "category": rng.choice(CATS, N).astype(object),
        "revenue": rng.integers(0, 1_000_000, N),
        "quantity": rng.integers(1, 50, N).astype(np.int32),
    }


def _schema():
    return Schema(
        "ssb",
        [
            FieldSpec("d_year", DataType.INT),
            FieldSpec("region", DataType.STRING),
            FieldSpec("category", DataType.STRING),
            FieldSpec("revenue", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("quantity", DataType.INT, role=FieldRole.METRIC),
        ],
    )


ST_CFG = {
    "dimensionsSplitOrder": ["d_year", "region", "category"],
    "functionColumnPairs": [
        "COUNT__*",
        "SUM__revenue",
        "AVG__quantity",
        "MIN__revenue",
        "MAX__revenue",
    ],
    "maxLeafRecords": 10000,
}


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(7)
    data = _data(rng)
    schema = _schema()
    cfg = TableConfig(
        name="ssb", indexing=IndexingConfig(star_tree_index_configs=[ST_CFG])
    )
    seg = build_segment(schema, data, "seg0", table_config=cfg)
    eng = QueryEngine()
    eng.register_table(schema, cfg)
    eng.add_segment("ssb", seg)
    conn = sqlite_from_data("ssb", data)
    return eng, conn, seg


def _check(env, sql, expect_star=True, sql_lite=None):
    eng, conn, seg = env
    res_star = eng.query(sql)
    res_scan = eng.query("SET useStarTree=false; " + sql)
    expected = conn.execute(sql_lite or sql).fetchall()
    assert_same_rows(res_star.rows, expected)
    assert_same_rows(res_scan.rows, expected)
    kinds = {k for _, k in res_star.stats.filter_index_uses}
    if expect_star:
        assert "startree" in kinds, res_star.stats.filter_index_uses
        assert res_star.stats.num_docs_scanned < res_scan.stats.num_docs_scanned
    else:
        assert "startree" not in kinds
    return res_star


def test_tree_built(env):
    _, _, seg = env
    st = seg.indexes["startree"]["st0"]
    assert st.split_order == ["d_year", "region", "category"]
    # finest level collapses 8000 rows into <= |years|*|regions|*|cats| combos
    assert st.levels[3].num_rows <= len(YEARS) * len(REGIONS) * len(CATS)
    # coarser prefix levels shrink monotonically down to the 1-row total
    assert st.levels[2].num_rows <= st.levels[3].num_rows
    assert st.levels[0].num_rows == 1


def test_groupby_sum(env):
    _check(env, "SELECT d_year, SUM(revenue) FROM ssb GROUP BY d_year")


def test_groupby_filtered(env):
    _check(
        env,
        "SELECT d_year, SUM(revenue) FROM ssb WHERE region = 'ASIA' GROUP BY d_year",
    )


def test_groupby_multi_dim_all_aggs(env):
    _check(
        env,
        "SELECT d_year, region, COUNT(*), SUM(revenue), AVG(quantity), "
        "MIN(revenue), MAX(revenue) FROM ssb GROUP BY d_year, region LIMIT 100",
    )


def test_aggregation_only(env):
    res = _check(env, "SELECT SUM(revenue), COUNT(*) FROM ssb")
    # no dims used -> level 0: exactly one pre-aggregated row scanned
    assert res.stats.num_docs_scanned == 1


def test_level_selection(env):
    eng, conn, seg = env
    st = seg.indexes["startree"]["st0"]
    res = eng.query("SELECT d_year, COUNT(*) FROM ssb GROUP BY d_year")
    assert res.stats.num_docs_scanned == st.levels[1].num_rows
    res2 = eng.query(
        "SELECT category, COUNT(*) FROM ssb GROUP BY category"
    )  # category is last in split order -> needs the finest level
    assert res2.stats.num_docs_scanned == st.levels[3].num_rows


def test_range_filter_on_dim(env):
    _check(
        env,
        "SELECT region, SUM(revenue) FROM ssb WHERE d_year > 1994 GROUP BY region",
    )


def test_having_order_limit(env):
    _check(
        env,
        "SELECT region, SUM(revenue) AS r FROM ssb GROUP BY region "
        "HAVING r > 0 ORDER BY r DESC LIMIT 3",
    )


def test_not_applicable_non_dim_filter(env):
    # filter on a metric column: tree cannot answer; scan path must serve it
    _check(
        env,
        "SELECT d_year, COUNT(*) FROM ssb WHERE quantity > 25 GROUP BY d_year",
        expect_star=False,
    )


def test_sum_rides_avg_pair_fields(env):
    # field-level storage is strictly more capable than Pinot's pair-level:
    # AVG__quantity stored (sum, count), which is exactly SUM's partial too
    _check(env, "SELECT d_year, SUM(quantity) FROM ssb GROUP BY d_year")


def test_not_applicable_unpaired_agg(env):
    # MIN(quantity) has no stored (quantity, min) field -> scan path serves it
    _check(
        env,
        "SELECT d_year, MIN(quantity) FROM ssb GROUP BY d_year",
        expect_star=False,
    )


def test_save_load_roundtrip(env, tmp_path):
    eng, conn, seg = env
    from pinot_tpu.segment.segment import ImmutableSegment

    path = str(tmp_path / "seg_star")
    seg.save(path)
    seg2 = ImmutableSegment.load(path)
    assert "startree" in seg2.indexes
    eng2 = QueryEngine()
    eng2.register_table(_schema(), TableConfig(name="ssb"))
    eng2.add_segment("ssb", seg2)
    sql = "SELECT d_year, region, SUM(revenue) FROM ssb GROUP BY d_year, region LIMIT 100"
    res = eng2.query(sql)
    assert_same_rows(res.rows, conn.execute(sql).fetchall())
    assert {k for _, k in res.stats.filter_index_uses} >= {"startree"}


def test_mixed_segments_merge(env):
    """One segment with a tree + one without must merge in one key space."""
    eng, conn, seg = env
    rng = np.random.default_rng(8)
    data2 = _data(rng)
    seg2 = build_segment(_schema(), data2, "seg1")  # no star tree
    eng2 = QueryEngine()
    eng2.register_table(_schema(), TableConfig(name="ssb"))
    eng2.add_segment("ssb", seg)
    eng2.add_segment("ssb", seg2)

    import sqlite3

    conn2 = sqlite_from_data("ssb", {k: np.concatenate([np.asarray(_data(np.random.default_rng(7))[k]), np.asarray(data2[k])]) for k in data2})
    sql = "SELECT d_year, SUM(revenue), COUNT(*) FROM ssb WHERE region != 'AFRICA' GROUP BY d_year"
    res = eng2.query(sql)
    assert_same_rows(res.rows, conn2.execute(sql).fetchall())
