"""Performance observatory (round 6): kernel cost capture + fallbacks,
roofline accounting, the per-table/per-shape perf ledger, cluster metric
federation, /debug/perf, and the bench-history regression gate."""
import json
import urllib.request

import numpy as np
import pytest

from pinot_tpu import ops
from pinot_tpu.cluster import Broker, Coordinator, ServerInstance
from pinot_tpu.cluster.rest import QueryServer
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.query.result import ExecutionStats
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.utils import perf
from pinot_tpu.utils.metrics import METRICS, MetricsRegistry, federate_prometheus, merge_registry_snapshots
from pinot_tpu.utils.slowlog import SlowQueryLog


def _schema(table="t"):
    return Schema(
        table,
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )


def _data(n, seed):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "v": rng.integers(0, 100, n),
    }


def _engine(table="t", n_segments=2, rows=150):
    eng = QueryEngine()
    eng.register_table(_schema(table))
    for i in range(n_segments):
        eng.add_segment(table, build_segment(_schema(table), _data(rows, 100 + i), f"seg{i}"))
    return eng


class _FakeCol:
    def __init__(self, codes=None, values=None, nulls=None):
        self.codes = codes
        self.values = values
        self.nulls = nulls


# ---------------------------------------------------------------------------
# capture_cost fallbacks
# ---------------------------------------------------------------------------
class TestCaptureCost:
    def test_auto_on_cpu_is_analytic_without_lowering(self):
        # auto mode on a CPU backend must not even touch fn (no extra
        # trace+lower on the tier-1 serving path)
        analytic = perf.analytic_cost(100, 8.0)
        got = perf.capture_cost(None, (), analytic)
        assert got is analytic and got.source == "analytic"

    def test_forced_xla_reads_cost_analysis_on_cpu(self):
        import jax
        import jax.numpy as jnp

        fn = jax.jit(lambda x: (x * x).sum())
        x = jnp.arange(1024, dtype=jnp.float32)
        analytic = perf.analytic_cost(1024, 4.0)
        got = perf.capture_cost(fn, (x,), analytic, force="xla")
        # CPU XLA reports cost_analysis (probed); if a backend ever stops,
        # the guarded fallback hands back the analytic estimate instead
        assert got.source in ("xla", "analytic")
        assert got.bytes_accessed > 0
        if got.source == "xla":
            assert got.flops > 0 and got.lower_ms > 0

    def test_lowering_failure_falls_back_to_analytic(self):
        class Exploding:
            def lower(self, *a):
                raise RuntimeError("backend without cost analysis")

        analytic = perf.analytic_cost(10, 4.0)
        got = perf.capture_cost(Exploding(), (1,), analytic, force="xla")
        assert got is analytic and got.source == "analytic"

    def test_missing_bytes_key_falls_back_but_keeps_lower_ms(self):
        class NoBytes:
            def lower(self, *a):
                return self

            def cost_analysis(self):
                return {"flops": 42.0}  # no 'bytes accessed' -> unusable

        analytic = perf.analytic_cost(10, 4.0)
        got = perf.capture_cost(NoBytes(), (1,), analytic, force="xla")
        assert got.source == "analytic" and got.lower_ms > 0

    def test_env_override_forces_analytic(self, monkeypatch):
        monkeypatch.setenv("PINOT_TPU_COST_SOURCE", "analytic")
        analytic = perf.analytic_cost(10, 4.0)
        got = perf.capture_cost(None, (), analytic)
        assert got is analytic

    def test_combine_sources(self):
        assert perf.combine_sources(None, "xla") == "xla"
        assert perf.combine_sources("xla", "xla") == "xla"
        assert perf.combine_sources("xla", "analytic") == "mixed"
        assert perf.combine_sources("analytic", None) == "analytic"


class TestAnalyticModel:
    def test_bytes_per_row_uses_stored_widths(self):
        cols = [
            _FakeCol(codes=np.zeros(4, np.int8)),  # dict codes at code width
            _FakeCol(values=np.zeros(4, np.int64), nulls=np.zeros(4, bool)),
        ]
        bpr = perf.analytic_bytes_per_row(cols, bitmap_params=1)
        assert bpr == pytest.approx(1 + 8 + 1 + 4 / 32)

    def test_groupby_flops_follow_one_hot_matmul(self):
        from pinot_tpu.ops.pallas_scan import matmul_flops_per_row

        c = perf.analytic_cost(1000, 8.0, kind="groupby", num_groups=50, num_entries=2)
        assert c.flops == pytest.approx(1000 * matmul_flops_per_row(50, 2))
        assert c.bytes_accessed == pytest.approx(8000.0)
        assert c.output_bytes > 0

    def test_aggregation_and_selection_kinds(self):
        agg = perf.analytic_cost(100, 4.0, kind="aggregation", num_entries=3)
        sel = perf.analytic_cost(100, 4.0, kind="selection")
        assert agg.flops == pytest.approx(600.0)
        assert sel.flops == pytest.approx(100.0)


class TestRoofline:
    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("PINOT_TPU_PEAK_HBM_BPS", "1e9")
        perf.peak_hbm_bytes_per_sec.cache_clear()
        try:
            assert perf.peak_hbm_bytes_per_sec() == 1e9
            # 5e8 bytes in 1s = 50% of a 1e9 peak
            assert perf.roofline_pct(5e8, 1.0) == pytest.approx(50.0)
        finally:
            perf.peak_hbm_bytes_per_sec.cache_clear()

    def test_unmeasurable_is_none(self):
        assert perf.roofline_pct(0.0, 1.0) is None
        assert perf.roofline_pct(100.0, 0.0) is None

    def test_cpu_fallback_peak_is_positive(self, monkeypatch):
        monkeypatch.delenv("PINOT_TPU_PEAK_HBM_BPS", raising=False)
        perf.peak_hbm_bytes_per_sec.cache_clear()
        try:
            assert perf.peak_hbm_bytes_per_sec() > 0
        finally:
            perf.peak_hbm_bytes_per_sec.cache_clear()


# ---------------------------------------------------------------------------
# engine integration: cost on stats, EXPLAIN ANALYZE, cached reuse
# ---------------------------------------------------------------------------
class TestEngineCostIntegration:
    def test_stats_carry_kernel_cost(self):
        eng = _engine(table="perfcost")
        out = eng.query("SELECT city, SUM(v) FROM perfcost GROUP BY city")
        s = out.stats
        assert s.kernel_bytes > 0 and s.kernel_flops > 0
        assert s.kernel_cost_source in ("analytic", "xla", "mixed")

    def test_cost_captured_once_not_relowered_on_hits(self):
        eng = _engine(table="perfreuse")
        sql = "SELECT city, SUM(v) FROM perfreuse GROUP BY city"
        first = eng.query(sql).stats
        second = eng.query(sql).stats
        # cold: compile wall time recorded; warm: plan-cache hit copies the
        # captured cost without re-lowering, and pays no compile
        assert first.compile_ms > 0
        assert second.compile_ms == 0.0
        assert second.kernel_bytes == pytest.approx(first.kernel_bytes)
        assert second.kernel_cost_source == first.kernel_cost_source

    def test_explain_analyze_interpret_pallas_shows_cost_columns(self, monkeypatch):
        # the acceptance shape: a Pallas-backed group-by scan on CPU tier-1
        # (interpret mode) surfaces per-operator Bytes/Flops/Roofline_Pct
        # through the analytic fallback
        monkeypatch.setenv("PINOT_TPU_SCAN_BACKEND", "interpret")
        ops.scan_backend.cache_clear()
        try:
            eng = _engine(table="perfinterp", rows=170)
            res = eng.query(
                "EXPLAIN ANALYZE SELECT city, SUM(v) FROM perfinterp GROUP BY city"
            )
            assert res.columns == [
                "Operator", "Operator_Id", "Parent_Id", "Actual_Ms", "Rows",
                "Bytes", "Flops", "Roofline_Pct",
            ]
            gb = [r for r in res.rows if str(r[0]).startswith(("GROUP_BY", "AGGREGATE"))]
            assert gb, res.rows
            op = gb[0]
            assert op[5] > 0 and op[6] > 0  # Bytes, Flops
            assert op[7] is None or op[7] > 0  # Roofline_Pct when fence measured
            # roofline must be measured somewhere in the plan: the fence-
            # owning COMBINE row or a TRACE(device_wait) span carries it
            roofs = [r[7] for r in res.rows if r[7] is not None]
            assert roofs and all(v > 0 for v in roofs)
            trace_launch = [r for r in res.rows if str(r[0]).startswith("TRACE(launch")]
            assert any(r[5] for r in trace_launch)  # span-level kernelBytes
        finally:
            ops.scan_backend.cache_clear()


# ---------------------------------------------------------------------------
# perf ledger
# ---------------------------------------------------------------------------
class TestPerfLedger:
    def test_record_snapshot_and_gauges(self):
        led = perf.PerfLedger(window=4)
        for i in range(6):  # overflow the window: deques stay bounded
            led.record(
                "t", "abc123", rows=1000, time_ms=10.0, kernel_bytes=8000.0,
                compile_ms=5.0 if i == 0 else 0.0, cache_hit=i > 0,
            )
        snap = led.snapshot()
        sh = snap["tables"]["t"]["shapes"]["abc123"]
        assert snap["tables"]["t"]["queries"] == 6
        assert sh["rowsPerSec"]["last"] == pytest.approx(100000.0)
        assert sh["planCacheHitRate"] == pytest.approx(5 / 6, abs=1e-3)
        assert sh["compileMsTotal"] == pytest.approx(5.0)
        assert sh["rooflinePct"]["last"] > 0
        assert sh["qps"] >= 0

    def test_global_ledger_exports_table_gauges(self):
        perf.PERF_LEDGER.record("gt", "fp", rows=100, time_ms=5.0, kernel_bytes=400.0)
        snap = METRICS.snapshot()
        assert snap["gauges"]["perf.gt.rowsPerSec"] == pytest.approx(20000.0)
        assert "perf.gt.bytesPerSec" in snap["gauges"]

    def test_sse_query_lands_in_global_ledger(self):
        eng = _engine(table="perfledger")
        eng.query("SELECT COUNT(*) FROM perfledger")
        snap = perf.PERF_LEDGER.snapshot()
        assert "perfledger" in snap["tables"]
        t = snap["tables"]["perfledger"]
        assert t["queries"] >= 1
        (shape,) = list(t["shapes"].values())[:1]
        assert shape["rowsPerSec"]["last"] > 0


# ---------------------------------------------------------------------------
# cluster metric federation
# ---------------------------------------------------------------------------
def _cluster(n_servers=2, n_segments=4, rows=150):
    coord = Coordinator(replication=2)
    for i in range(n_servers):
        coord.register_server(ServerInstance(f"server{i}"))
    coord.add_table(_schema(), TableConfig(name="t"))
    for i in range(n_segments):
        coord.add_segment("t", build_segment(_schema(), _data(rows, 100 + i), f"seg{i}"))
    return coord


class TestFederation:
    def test_merge_semantics(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("queries").inc(3)
        b.counter("queries").inc(4)
        a.gauge("level").set(1.0)
        b.gauge("level").set(2.0)
        a.timer("lat").update(10.0)
        b.timer("lat").update(30.0)
        a.histogram("h").update(1.0)
        b.histogram("h").update(1.0)
        merged = merge_registry_snapshots({"s0": a, "s1": b})
        assert merged["counters"]["queries"] == 7  # SUM
        assert merged["gauges"]["level"] == 2.0  # LAST (lexicographic s1)
        assert merged["timers"]["lat"]["count"] == 2
        assert merged["timers"]["lat"]["maxMs"] == 30.0  # MAX
        assert merged["histograms"]["h"]["count"] == 2  # bucket-wise SUM
        assert sum(merged["histograms"]["h"]["counts"]) == 2

    def test_federate_prometheus_labels_sources(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("server.queries").inc(2)
        b.counter("server.queries").inc(5)
        text = federate_prometheus({"s0": a, "s1": b})
        assert 'pinot_server_queries_total{server="s0"} 2' in text
        assert 'pinot_server_queries_total{server="s1"} 5' in text
        assert "pinot_cluster_server_queries_total 7" in text

    def test_broker_federates_server_registries(self):
        coord = _cluster()
        broker = Broker(coord)
        for _ in range(3):
            broker.query("SELECT city, COUNT(*) FROM t GROUP BY city")
        regs = broker.federated_registries()
        assert set(regs) == {"server0", "server1"}
        text = broker.federated_prometheus()
        assert 'server="server0"' in text and 'server="server1"' in text
        assert "pinot_cluster_server_queries_total" in text
        snap = broker.federated_snapshot()
        per_server = sum(
            r["counters"].get("server.queries", 0) for r in snap["perServer"].values()
        )
        assert snap["cluster"]["counters"]["server.queries"] == per_server > 0

    def test_rest_metrics_endpoint_serves_federation(self):
        coord = _cluster()
        broker = Broker(coord)
        broker.query("SELECT COUNT(*) FROM t")
        srv = QueryServer(broker).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(base + "/metrics?format=prometheus") as r:
                text = r.read().decode()
            assert 'server="server0"' in text and "pinot_cluster_" in text
            with urllib.request.urlopen(base + "/debug/perf") as r:
                payload = json.loads(r.read().decode())
            assert "tables" in payload and "t" in payload["tables"]
            assert "caches" in payload
        finally:
            srv.stop()

    def test_debug_perf_route_on_plain_engine(self):
        srv = QueryServer(_engine(table="perfroute")).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(
                base + "/query", data=json.dumps({"sql": "SELECT COUNT(*) FROM perfroute"}).encode()
            ) as r:
                r.read()
            with urllib.request.urlopen(base + "/debug/perf") as r:
                payload = json.loads(r.read().decode())
            assert "tables" in payload
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# slow log perf fields
# ---------------------------------------------------------------------------
class TestSlowLogPerfFields:
    def test_entry_carries_kernel_cost_and_roofline(self):
        class R:
            stats = ExecutionStats()
            rows = [(1,)]

        R.stats.time_ms = 10.0
        R.stats.num_docs_scanned = 1000
        R.stats.kernel_bytes = 8.0e6
        R.stats.kernel_flops = 2.0e6
        R.stats.kernel_cost_source = "analytic"
        R.stats.compile_ms = 3.0
        R.stats.device_ms = 8.0
        log = SlowQueryLog(capacity=4, slow_ms=1e9)
        entry = log.record("SELECT 1", "fp", result=R())
        assert entry["kernelBytes"] == 8.0e6
        assert entry["costSource"] == "analytic"
        assert entry["rooflinePct"] > 0
        assert entry["rowsPerSec"] == pytest.approx(100000.0)

    def test_entry_without_cost_stays_lean(self):
        class R:
            stats = ExecutionStats()
            rows = []

        log = SlowQueryLog(capacity=4, slow_ms=1e9)
        entry = log.record("SELECT 1", "fp", result=R())
        assert "kernelBytes" not in entry


# ---------------------------------------------------------------------------
# bench-history regression gate
# ---------------------------------------------------------------------------
def _rec(scale=1.0, backend="xla", rows=1000, rv=0.02):
    return {
        "schema": 1,
        "bench": "ssb_groupby",
        "backend": backend,
        "rows": rows,
        "metrics": {
            "kernel_rows_per_sec": 1e6 * scale,
            "e2e_rows_per_sec": 5e5 * scale,
            "warm_p50_rows_per_sec": 8e5 * scale,
            "effective_bytes_per_sec": 9e6 * scale,
        },
        "noise": {"run_variance": rv},
    }


class TestRegressionGate:
    def test_identical_records_pass(self):
        v = perf.check_regression(_rec(), _rec())
        assert v["ok"] and len(v["checks"]) == 4

    def test_twenty_percent_drop_always_fails(self):
        # the acceptance bar: a true >=20% throughput regression trips the
        # gate regardless of measured noise
        v = perf.check_regression(_rec(scale=0.80), _rec(), threshold=None)
        assert not v["ok"] and v["reasons"]
        v_noisy = perf.check_regression(_rec(scale=0.80, rv=10.0), _rec(rv=10.0))
        assert not v_noisy["ok"]  # allowance clamps below 20%

    def test_small_drop_within_noise_passes(self):
        assert perf.check_regression(_rec(scale=0.90), _rec())["ok"]

    def test_incomparable_records_fail(self):
        v = perf.check_regression(_rec(backend="interpret"), _rec())
        assert not v["ok"] and any("incomparable" in r for r in v["reasons"])

    def test_empty_comparison_fails(self):
        v = perf.check_regression({"metrics": {}}, {"metrics": {}})
        assert not v["ok"] and "no gated metrics" in v["reasons"][0]

    def test_allowance_clamps(self):
        assert perf.regression_allowance(_rec(rv=0.0)) == pytest.approx(0.15)
        assert perf.regression_allowance(_rec(rv=1.0)) == pytest.approx(0.19)

    def test_history_roundtrip_skips_corrupt_lines(self, tmp_path):
        p = tmp_path / "hist.jsonl"
        perf.append_bench_history(str(p), _rec())
        p.write_text(p.read_text() + "{torn line\n")
        perf.append_bench_history(str(p), _rec(scale=1.1))
        hist = perf.load_bench_history(str(p))
        assert len(hist) == 2
        assert hist[-1]["metrics"]["kernel_rows_per_sec"] == pytest.approx(1.1e6)

    def test_bench_record_distills_report(self):
        report = {
            "value": 123.0,
            "value_e2e": 45.0,
            "run_variance": 0.07,
            "rows": 10,
            "backend": "xla",
            "effective_bytes_per_sec": 999.0,
            "distinct_literal_sweep": {"warm_p50_rows_per_sec": 77.0},
            "plan_cache": {"hit_rate": 0.9},
            "roofline": {"device_kind": "cpu", "kernel_roofline_pct": 1.5,
                         "cost_bytes_per_sec": 1000.0},
        }
        rec = perf.bench_record(report)
        assert rec["metrics"]["kernel_rows_per_sec"] == 123.0
        assert rec["metrics"]["warm_p50_rows_per_sec"] == 77.0
        assert rec["metrics"]["roofline_pct"] == 1.5
        assert rec["noise"]["run_variance"] == 0.07

    def test_cli_perf_check_exits_nonzero_on_synthetic_regression(self, tmp_path, capsys):
        from pinot_tpu.tools.cli import main

        hist = tmp_path / "bench_history.jsonl"
        base = tmp_path / "BENCH_BASELINE.json"
        base.write_text(json.dumps(_rec()))
        perf.append_bench_history(str(hist), _rec(scale=0.75))  # injected -25%
        rc = main(["perf", "--check", "--history", str(hist), "--baseline", str(base)])
        assert rc == 1
        out = capsys.readouterr().out
        assert "FAIL" in out

    def test_cli_perf_check_passes_on_healthy_run(self, tmp_path, capsys):
        from pinot_tpu.tools.cli import main

        hist = tmp_path / "bench_history.jsonl"
        base = tmp_path / "BENCH_BASELINE.json"
        base.write_text(json.dumps(_rec()))
        perf.append_bench_history(str(hist), _rec(scale=1.02))
        rc = main(["perf", "--check", "--history", str(hist), "--baseline", str(base)])
        assert rc == 0

    def test_cli_perf_check_fails_on_missing_history(self, tmp_path):
        from pinot_tpu.tools.cli import main

        base = tmp_path / "BENCH_BASELINE.json"
        base.write_text(json.dumps(_rec()))
        rc = main([
            "perf", "--check",
            "--history", str(tmp_path / "nope.jsonl"),
            "--baseline", str(base),
        ])
        assert rc == 1


@pytest.mark.slow
def test_repo_bench_baseline_gate_passes():
    """The committed bench history vs the pinned baseline must pass the
    gate — this is the regression check CI runs after a real bench run."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hist = os.path.join(root, "bench_history.jsonl")
    base = os.path.join(root, "BENCH_BASELINE.json")
    if not (os.path.exists(hist) and os.path.exists(base)):
        pytest.skip("no committed bench artifacts")
    latest = perf.load_bench_history(hist)[-1]
    with open(base, "r", encoding="utf-8") as f:
        baseline = json.load(f)
    verdict = perf.check_regression(latest, baseline)
    assert verdict["ok"], verdict["reasons"]
