"""Overload-safe serving tests (round 11): cost-based token-bucket
admission, HBM/host byte reservations, the runaway-query watchdog, graceful
degradation, and their REST / breaker / cache interactions.

Determinism: admission tests inject the bucket clock (the simulated arrival
schedule IS the clock, host speed is irrelevant), watchdog tests inject a
counting clock, and the overload acceptance sweep reuses the bench.py
methodology — offered load is simulated, outcomes are exact counts.
"""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from pinot_tpu.cluster import Broker, Coordinator, ServerInstance
from pinot_tpu.cluster.admission import (
    AdmissionController,
    DegradationController,
    QueryCost,
    QueryKilledError,
    QueryWatchdog,
    ReservationError,
    ResourceBudget,
    ResourceGovernor,
    TooManyRequestsError,
    estimate_query_cost,
    pipeline_depth_under_pressure,
)
from pinot_tpu.query.safety import AdmissionError
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import SegmentsConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import parse_query
from pinot_tpu.utils.metrics import METRICS


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _data(n, seed, t0=1_700_000_000_000):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "v": rng.integers(0, 100, n),
        "ts": t0 + rng.integers(0, 86_400_000, n).astype(np.int64),
    }


def _cluster(n_servers=2, replication=2, n_segments=4, rows=200, server_budget=None):
    """Deterministic small cluster; `server_budget` bytes installs an
    explicit HBM reservation ledger per server (None = coordinator default)."""
    coord = Coordinator(replication=replication)
    for i in range(n_servers):
        budget = (
            ResourceBudget(server_budget, gauge=f"server.reservedBytes.server{i}")
            if server_budget is not None
            else None
        )
        coord.register_server(ServerInstance(f"server{i}", budget=budget))
    coord.add_table(_schema(), TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    for i in range(n_segments):
        coord.add_segment("t", build_segment(_schema(), _data(rows, seed=100 + i), f"seg{i}"))
    return coord


def _governor(rate=0.0, burst=None, max_queue=8, host_bytes=1 << 30,
              runaway_ms=0.0, kill_at=0.0):
    return ResourceGovernor(
        admission=AdmissionController(
            rate_units_per_s=rate, burst_units=burst, max_queue=max_queue
        ),
        host_budget=ResourceBudget(host_bytes, gauge="admission.hostReservedBytes"),
        watchdog=QueryWatchdog(runaway_ms=runaway_ms, pressure_kill_at=kill_at),
        degrade=DegradationController(),
    )


def _sql(i=0):
    # distinct literal per call: misses the result cache, shares ONE
    # parameterized plan shape (literals ride as device args)
    return (
        "SELECT city, COUNT(*), SUM(v) FROM t "
        f"WHERE v < {50 + i % 40} GROUP BY city ORDER BY city"
    )


# ---------------------------------------------------------------------------
# admission controller units (injected clock — no sleeps, no luck)
# ---------------------------------------------------------------------------
class TestAdmissionController:
    def test_disabled_by_default(self):
        adm = AdmissionController()  # rate 0 = off
        for _ in range(100):
            adm.admit("q", units=50.0)
        assert adm.snapshot()["rate"] == 0.0

    def test_token_bucket_charges_and_refills_on_injected_clock(self):
        sim = [0.0]
        adm = AdmissionController(rate_units_per_s=2.0, burst_units=2.0, max_queue=0)
        adm.clock = lambda: sim[0]
        adm.admit("q1")  # burst 2.0 -> 1.0
        adm.admit("q2")  # -> 0.0
        with pytest.raises(TooManyRequestsError) as ei:
            adm.admit("q3")
        assert ei.value.query_id == "q3"
        sim[0] += 1.0  # repays 2 units
        adm.admit("q4")
        adm.admit("q5")
        assert METRICS.counter("admission.shed").value == 1
        assert METRICS.counter("admission.admitted").value == 4

    def test_oversized_query_is_clamped_to_burst_not_starved(self):
        sim = [0.0]
        adm = AdmissionController(rate_units_per_s=1.0, burst_units=4.0, max_queue=0)
        adm.clock = lambda: sim[0]
        adm.admit("huge", units=1e9)  # min(units, burst): servable, drains bucket
        with pytest.raises(TooManyRequestsError):
            adm.admit("next")

    def test_queue_full_sheds_immediately(self):
        sim = [0.0]
        adm = AdmissionController(rate_units_per_s=1.0, burst_units=1.0, max_queue=0)
        adm.clock = lambda: sim[0]
        adm.admit("q1")
        with pytest.raises(TooManyRequestsError, match="queue full"):
            adm.admit("q2")
        assert adm.snapshot()["waiting"] == 0

    def test_wait_budget_exhaustion_sheds(self):
        sim = [0.0]
        adm = AdmissionController(
            rate_units_per_s=1.0, burst_units=1.0, max_queue=4, max_wait_ms=0.0
        )
        adm.clock = lambda: sim[0]
        adm.admit("q1")
        with pytest.raises(TooManyRequestsError, match="without a token"):
            adm.admit("q2")
        assert adm.snapshot()["waiting"] == 0  # bounded queue drained

    def test_low_priority_sheds_before_queueing(self):
        sim = [0.0]
        adm = AdmissionController(rate_units_per_s=1.0, burst_units=1.0, max_queue=8)
        adm.clock = lambda: sim[0]
        adm.admit("q1")
        with pytest.raises(TooManyRequestsError, match="low-priority"):
            adm.admit("q2", priority=-1)

    def test_waiter_admitted_when_tokens_refill(self):
        # real clock: rate 200 units/s repays one unit in ~5 ms — the waiter
        # parks on the condition and wakes within the 500 ms wait budget
        adm = AdmissionController(rate_units_per_s=200.0, burst_units=1.0, max_queue=8)
        adm.admit("q1")
        adm.admit("q2")  # waits ~5 ms, then admitted
        assert METRICS.counter("admission.admittedAfterWait").value >= 1


# ---------------------------------------------------------------------------
# byte-reservation ledger units
# ---------------------------------------------------------------------------
class TestResourceBudget:
    def test_reserve_release_and_peak(self):
        b = ResourceBudget(1000, gauge="test.reservedBytes")
        t1 = b.reserve(400)
        t2 = b.reserve(500)
        assert b.in_use == 900 and b.peak == 900
        assert METRICS.gauge("test.reservedBytes").value == 900.0
        assert b.release(t1) == 400
        assert b.in_use == 500
        b.release(t2)
        assert b.in_use == 0 and b.peak == 900
        assert METRICS.gauge("test.reservedBytes").value == 0.0

    def test_overcommit_raises_and_leaves_ledger_intact(self):
        b = ResourceBudget(1000)
        b.reserve(900)
        with pytest.raises(ReservationError) as ei:
            b.reserve(200, what="query working set", query_id="qx")
        assert ei.value.query_id == "qx"
        assert isinstance(ei.value, AdmissionError)  # REST 503 family
        assert b.in_use == 900 and b.snapshot()["reservations"] == 1

    def test_cache_charges_share_the_same_ledger(self):
        b = ResourceBudget(1000)
        assert b.try_charge(600)
        with pytest.raises(ReservationError):
            b.reserve(500)  # queries see cache-held bytes
        assert not b.try_charge(600)  # and caches see reservations
        b.uncharge(600)
        b.uncharge(999)  # clamps at zero, never negative
        assert b.in_use == 0

    def test_release_is_idempotent_per_ticket(self):
        b = ResourceBudget(100)
        t = b.reserve(40)
        assert b.release(t) == 40
        assert b.release(t) == 0
        assert b.in_use == 0


# ---------------------------------------------------------------------------
# cost estimation
# ---------------------------------------------------------------------------
class TestCostEstimation:
    def test_cost_scales_with_segment_stats_and_group_by(self):
        coord = _cluster()
        metas = coord.tables["t"].segment_meta.values()
        scan = estimate_query_cost(parse_query("SELECT COUNT(*) FROM t"), metas)
        grouped = estimate_query_cost(parse_query(_sql()), metas)
        assert scan.rows == 4 * 200
        assert scan.hbm_bytes > 0  # coordinator metadata carries segment bytes
        assert scan.group_cardinality == 0
        assert grouped.group_cardinality > 0
        assert grouped.units > scan.units >= 1.0
        assert grouped.host_bytes > scan.host_bytes


# ---------------------------------------------------------------------------
# deterministic overload acceptance: 3x offered load sheds, never crashes
# ---------------------------------------------------------------------------
class TestOverloadAcceptance:
    def test_3x_offered_load_sheds_structured_and_keeps_admitted_latency(self):
        import time

        host_budget_bytes = 1 << 30
        server_budget_bytes = 64 << 20
        coord = _cluster(server_budget=server_budget_bytes)
        broker = Broker(coord)
        for i in range(3):
            broker.query(_sql(i))  # warm: parse/plan/compile

        # uncontended baseline (env-default governor: admission off)
        base_ms = []
        for i in range(30):
            t0 = time.perf_counter()
            broker.query(_sql(i))
            base_ms.append((time.perf_counter() - t0) * 1000)
        uncontended_p99 = float(np.percentile(base_ms, 99))
        capacity_qps = 1000.0 / float(np.median(base_ms))

        unit_cost = estimate_query_cost(
            parse_query(_sql()), coord.tables["t"].segment_meta.values()
        ).units
        gov = _governor(
            rate=capacity_qps * unit_cost,
            burst=2 * unit_cost,
            max_queue=0,
            host_bytes=host_budget_bytes,
        )
        sim = [0.0]
        gov.admission.clock = lambda: sim[0]
        broker.governor = gov

        offered_qps = 3.0 * capacity_qps
        admitted, admitted_ms, shed_ids = 0, [], []
        for i in range(90):
            sim[0] += 1.0 / offered_qps
            t0 = time.perf_counter()
            try:
                broker.query(_sql(i))
            except TooManyRequestsError as e:
                shed_ids.append(e.query_id)
            else:
                admitted += 1
                admitted_ms.append((time.perf_counter() - t0) * 1000)

        # sheds happened, were structured, and carried the minted query id
        assert shed_ids and all(qid for qid in shed_ids)
        # bucket math: ~1/3 admitted at 3x offered load (plus the burst)
        assert 90 // 3 <= admitted <= 90 // 3 + int(2 * unit_cost / unit_cost) + 2
        # admitted queries are NOT degraded by the shed traffic
        assert float(np.percentile(admitted_ms, 99)) <= 2.0 * uncontended_p99
        # reservations never exceeded any budget (gauge-backed high-water)
        assert 0 < gov.host_budget.peak <= host_budget_bytes
        for name in ("server0", "server1"):
            srv = coord.servers[name]
            assert 0 < srv.budget.peak <= server_budget_bytes
            assert METRICS.gauge(f"server.reservedBytes.{name}").value == 0.0
        assert METRICS.gauge("admission.hostReservedBytes").value == 0.0
        # nothing queued unboundedly, nothing leaked
        snap = gov.snapshot()
        assert snap["admission"]["waiting"] == 0
        assert snap["hostBudget"]["inUseBytes"] == 0
        assert snap["watchdog"]["activeQueries"] == 0


# ---------------------------------------------------------------------------
# runaway-query watchdog
# ---------------------------------------------------------------------------
class TestWatchdog:
    def test_lazy_runaway_kill_on_injected_clock(self):
        wd = QueryWatchdog(runaway_ms=100.0)
        tick = [0.0]
        wd.clock = lambda: tick[0]
        wd.register("q1", reserved_bytes=123, priority=0)
        assert wd.kill_reason("q1") is None  # within budget
        tick[0] = 0.2  # 200 ms elapsed > 100 ms ceiling
        reason = wd.kill_reason("q1")
        assert reason and "runaway" in reason
        rec = wd.kill_log[-1]
        assert rec.query_id == "q1" and rec.reserved_bytes == 123
        assert rec.elapsed_ms == pytest.approx(200.0)
        wd.deregister("q1")
        assert wd.snapshot()["activeQueries"] == 0

    def test_explicit_kill_and_unknown_query(self):
        wd = QueryWatchdog()
        wd.register("q1")
        assert wd.kill("q1", "operator request")
        assert not wd.kill("q1", "twice")  # already dead
        assert not wd.kill("ghost", "never registered")
        assert wd.kill_reason("q1") == "operator request"

    def test_pressure_patrol_prefers_low_priority_then_largest(self):
        wd = QueryWatchdog(pressure_kill_at=0.9)
        wd.register("big", reserved_bytes=1 << 20, priority=0)
        wd.register("small-low", reserved_bytes=1 << 10, priority=-1)
        assert wd.patrol(0.5) is None  # below threshold
        rec = wd.patrol(0.95)
        assert rec is not None and rec.query_id == "small-low"
        rec2 = wd.patrol(0.95)  # next victim: the remaining query
        assert rec2 is not None and rec2.query_id == "big"

    def test_cluster_kill_releases_resources_and_returns_partial(self):
        coord = _cluster()
        broker = Broker(coord)
        broker.query(_sql())  # warm
        # isolated governor: the env default shares the process host budget
        # with the plan caches, whose resident bytes are not this query's
        gov = _governor()
        broker.governor = gov
        # maxRuntimeMs=0.001: the first between-kernel probe is already past
        # the ceiling — a deterministic mid-flight kill without sleeps
        out = broker.query(
            "SET trace = true; SET allowPartialResults = true; "
            "SET maxRuntimeMs = 0.001; " + _sql()
        )
        assert out.stats.partial_result is True
        kills = [e for e in out.stats.exceptions if e.get("errorCode") == "QUERY_KILLED"]
        assert kills and "runaway" in kills[0]["reason"]
        # reservation released, watchdog drained, kill record retained
        assert gov.host_budget.in_use == 0
        assert gov.watchdog.snapshot()["activeQueries"] == 0
        assert any(r.query_id == out.stats.query_id for r in gov.watchdog.kill_log)
        # kill record in the slow log entry (top-level "kill" field)
        entry = broker.slow_queries.snapshot(limit=1)[0]
        assert entry["kill"]["errorCode"] == "QUERY_KILLED"
        assert entry["queryId"] == out.stats.query_id
        # ... and in the trace tree as a span annotation
        def spans_with_kill(node):
            found = []
            if isinstance(node, dict):
                if "killed" in node.get("attrs", {}):
                    found.append(node)
                for c in node.get("children", []):
                    found.extend(spans_with_kill(c))
            return found
        assert spans_with_kill(out.stats.trace)

    def test_cluster_kill_without_partial_raises_structured(self):
        coord = _cluster()
        broker = Broker(coord)
        broker.query(_sql())  # warm
        broker.governor = _governor()  # isolated ledger (see partial test)
        with pytest.raises(QueryKilledError) as ei:
            broker.query("SET maxRuntimeMs = 0.001; " + _sql())
        assert ei.value.query_id is not None
        assert broker.governor.host_budget.in_use == 0
        assert METRICS.counter("broker.queriesKilled").value >= 1


# ---------------------------------------------------------------------------
# breaker x admission isolation
# ---------------------------------------------------------------------------
class TestBreakerAdmissionIsolation:
    def test_shed_query_never_touches_breaker_or_stats(self, monkeypatch):
        coord = _cluster()
        broker = Broker(coord)
        broker.query(_sql())  # warm
        punished = []
        monkeypatch.setattr(
            broker.server_stats, "punish",
            lambda server, **kw: punished.append(server),
        )
        gov = _governor(rate=1.0, burst=1e-9, max_queue=0)
        sim = [0.0]
        gov.admission.clock = lambda: sim[0]
        gov.admission.admit("drain")  # consume the initial burst
        broker.governor = gov  # frozen clock: every query from here sheds
        for _ in range(5):
            with pytest.raises(TooManyRequestsError):
                broker.query(_sql())
        assert punished == []
        for name in coord.servers:
            assert broker.health.consecutive_failures(name) == 0
            assert broker.health.state(name) == "closed"

    def test_capacity_rejection_fails_over_without_punish_or_breaker(self, monkeypatch):
        coord = _cluster(server_budget=64 << 20)
        baseline = Broker(coord).query(_sql()).rows
        # server0's HBM ledger is committed to a phantom tenant: every
        # reserve() there fails, segments must fail over to server1
        coord.servers["server0"].budget = ResourceBudget(16)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        punished = []
        monkeypatch.setattr(
            broker.server_stats, "punish",
            lambda server, **kw: punished.append(server),
        )
        out = broker.query(_sql())
        assert out.rows == baseline  # failover absorbed the capacity fault
        assert punished == []
        assert broker.health.consecutive_failures("server0") == 0
        assert broker.health.state("server0") == "closed"
        codes = {e["errorCode"] for e in out.stats.exceptions}
        assert "SERVER_OUT_OF_CAPACITY" in codes
        assert METRICS.counter("broker.scatterCapacityRejections").value >= 1

    def test_every_replica_out_of_capacity_is_structured_not_scatter_error(self):
        coord = _cluster(server_budget=64 << 20)
        for s in coord.servers.values():
            s.budget = ResourceBudget(16)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        with pytest.raises(ReservationError) as ei:
            broker.query(_sql())
        assert ei.value.query_id is not None
        for name in coord.servers:
            assert broker.health.state(name) == "closed"

    def test_killed_query_punishes_exactly_once(self, monkeypatch):
        coord = _cluster()
        broker = Broker(coord)
        broker.query(_sql())  # warm
        punished = []
        monkeypatch.setattr(
            broker.server_stats, "punish",
            lambda server, **kw: punished.append(server),
        )
        out = broker.query(
            "SET allowPartialResults = true; SET maxRuntimeMs = 0.001; " + _sql()
        )
        assert out.stats.partial_result is True
        assert len(punished) == 1  # exactly once, not per retry round
        for name in coord.servers:
            assert broker.health.consecutive_failures(name) == 0

    def test_concurrent_mixed_outcomes_leave_ledgers_clean(self):
        coord = _cluster()
        broker = Broker(coord)
        broker.query(_sql())  # warm
        unit_cost = estimate_query_cost(
            parse_query(_sql()), coord.tables["t"].segment_meta.values()
        ).units
        gov = _governor(rate=1.0, burst=8.0 * unit_cost, max_queue=0)
        sim = [0.0]
        gov.admission.clock = lambda: sim[0]
        broker.governor = gov  # 8 queries' worth of tokens: half of 16 shed
        outcomes = {"ok": 0, "shed": 0, "other": 0}
        olock = threading.Lock()

        def worker(i):
            try:
                broker.query(_sql(i))
            except TooManyRequestsError:
                with olock:
                    outcomes["shed"] += 1
            except Exception:
                with olock:
                    outcomes["other"] += 1
            else:
                with olock:
                    outcomes["ok"] += 1

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert outcomes["other"] == 0
        assert outcomes["ok"] + outcomes["shed"] == 16
        assert outcomes["ok"] >= 8 and outcomes["shed"] >= 1
        assert gov.host_budget.in_use == 0
        assert gov.snapshot()["admission"]["waiting"] == 0
        assert gov.snapshot()["watchdog"]["activeQueries"] == 0
        for name in coord.servers:
            assert broker.health.consecutive_failures(name) == 0


# ---------------------------------------------------------------------------
# graceful degradation
# ---------------------------------------------------------------------------
class TestDegradation:
    def test_pressure_ladder_levels_and_flags(self):
        d = DegradationController()
        assert d.update(0.5) == 0 and d.result_cache_enabled()
        assert d.update(0.70) == 1
        assert not d.result_cache_enabled() and d.shed_low_priority()
        assert d.update(0.85) == 2
        assert d.update(0.95) == 3
        assert METRICS.gauge("admission.pressureLevel").value == 3.0
        assert d.update(0.1) == 0  # pressure release restores everything

    def test_pipeline_depth_shrinks_then_serializes(self):
        assert pipeline_depth_under_pressure(4, 0) == 4
        assert pipeline_depth_under_pressure(4, 1) == 4
        assert pipeline_depth_under_pressure(4, 2) == 3
        assert pipeline_depth_under_pressure(4, 3) == 1  # fully serialized
        assert pipeline_depth_under_pressure(1, 2) == 1  # floor

    def test_low_priority_shed_under_host_pressure(self):
        gov = _governor(host_bytes=1000)
        gov.host_budget.reserve(800)  # occupancy 0.8 -> level 1
        ctx = parse_query("SET isSecondaryWorkload = true; SELECT COUNT(*) FROM t")
        cost = QueryCost(rows=10, hbm_bytes=10, group_cardinality=0, host_bytes=10)
        with pytest.raises(TooManyRequestsError, match="low-priority"):
            gov.admit("q-low", ctx, cost)
        # a normal-priority query still gets through at level 1
        grant = gov.admit("q-norm", parse_query("SELECT COUNT(*) FROM t"), cost)
        grant.close()
        assert gov.host_budget.in_use == 800  # only the phantom reservation

    def test_result_cache_bypassed_under_pressure(self):
        coord = _cluster()
        broker = Broker(coord)
        sql = "SET useResultCache = true; " + _sql()
        broker.query(sql)  # populate
        assert broker.query(sql).stats.result_cache == "hit"
        # real pressure, not a poked level: admit() recomputes the level
        # from occupancy on every query, so only a held reservation sticks
        gov = _governor(host_bytes=32 << 20)
        broker.governor = gov
        # 75% reserved -> level 1, with headroom left for the query's own
        # ~3 MB working-set reservation (bypass, not rejection)
        ticket = gov.host_budget.reserve(int(0.75 * (32 << 20)))
        # bypassed = the cache was never consulted, so no hit/miss at all
        assert getattr(broker.query(sql).stats, "result_cache", None) is None
        gov.host_budget.release(ticket)  # pressure drains -> cache resumes
        assert broker.query(sql).stats.result_cache == "hit"


# ---------------------------------------------------------------------------
# cache byte-accounting against the shared host budget
# ---------------------------------------------------------------------------
class TestCacheBudgetUnification:
    def test_lru_cache_charges_and_releases_budget(self):
        from pinot_tpu.utils.cache import LruCache

        budget = ResourceBudget(10_000)
        c = LruCache(max_entries=64, name="test.cache", budget=budget)
        c.put("a", np.zeros(500, dtype=np.int8))  # ~500 bytes + overhead
        assert budget.in_use > 0
        held = budget.in_use
        c.put("b", np.zeros(500, dtype=np.int8))
        assert budget.in_use > held
        c.invalidate("a")
        c.invalidate("b")
        assert budget.in_use == 0

    def test_full_budget_forces_eviction_not_growth(self):
        from pinot_tpu.utils.cache import LruCache

        budget = ResourceBudget(10_000)
        budget.reserve(9_000)  # queries hold most of the ledger
        c = LruCache(max_entries=64, name="test.cache", budget=budget)
        for i in range(10):
            c.put(f"k{i}", np.zeros(400, dtype=np.int8))
        # the cache never pushed the ledger past its budget: it evicted
        assert budget.peak <= 10_000
        assert len(c) < 10
        c.clear()
        assert budget.in_use == 9_000  # only the query reservation remains

    def test_entry_too_big_for_remaining_budget_is_dropped(self):
        from pinot_tpu.utils.cache import LruCache

        budget = ResourceBudget(1_000)
        budget.reserve(900)
        c = LruCache(max_entries=64, name="test.cache", budget=budget)
        c.put("big", np.zeros(5_000, dtype=np.int8))
        assert c.get("big") is None and len(c) == 0
        assert budget.in_use == 900

    def test_broker_result_cache_rides_the_governor_host_budget(self):
        coord = _cluster()
        broker = Broker(coord)
        host = broker.governor.host_budget
        assert broker.result_cache.budget is host
        before = host.in_use
        broker.query("SET useResultCache = true; " + _sql())
        assert host.in_use > before  # cached rows are ledgered bytes
        broker.result_cache.clear()
        assert host.in_use == before

    def test_plan_cache_attached_to_process_budget(self):
        from pinot_tpu.query.planner import _PLAN_CACHE

        coord = _cluster()
        broker = Broker(coord)
        assert _PLAN_CACHE.budget is broker.governor.host_budget


# ---------------------------------------------------------------------------
# REST surface parity
# ---------------------------------------------------------------------------
class TestRestOverloadSurface:
    def _post(self, port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/query/sql",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    def _get(self, port, path):
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    def test_shed_maps_to_429_with_request_id(self):
        from pinot_tpu.cluster.rest import QueryServer

        broker = Broker(_cluster())
        gov = _governor(rate=1.0, burst=1e-9, max_queue=0)
        sim = [0.0]
        gov.admission.clock = lambda: sim[0]
        gov.admission.admit("drain")  # consume the initial burst
        broker.governor = gov  # frozen clock: the POST below sheds
        srv = QueryServer(broker).start()
        try:
            code, payload = self._post(srv.port, {"sql": _sql()})
            assert code == 429
            assert payload["errorCode"] == "TOO_MANY_REQUESTS_ERROR"
            assert payload["requestId"]
        finally:
            srv.stop()

    def test_capacity_maps_to_503_out_of_capacity(self):
        from pinot_tpu.cluster.rest import QueryServer

        coord = _cluster()
        for s in coord.servers.values():
            s.budget = ResourceBudget(16)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        srv = QueryServer(broker).start()
        try:
            code, payload = self._post(srv.port, {"sql": _sql()})
            assert code == 503
            assert payload["errorCode"] == "SERVER_OUT_OF_CAPACITY"
            assert payload["requestId"]
        finally:
            srv.stop()

    def test_kill_maps_to_503_query_killed_with_reason(self):
        from pinot_tpu.cluster.rest import QueryServer

        broker = Broker(_cluster())
        broker.query(_sql())  # warm so the killed run reaches the probe fast
        srv = QueryServer(broker).start()
        try:
            code, payload = self._post(
                srv.port, {"sql": "SET maxRuntimeMs = 0.001; " + _sql()}
            )
            assert code == 503
            assert payload["errorCode"] == "QUERY_KILLED"
            assert payload["requestId"]
            assert "runaway" in payload["reason"]
        finally:
            srv.stop()

    def test_killed_partial_carries_exception_detail_at_200(self):
        from pinot_tpu.cluster.rest import QueryServer

        broker = Broker(_cluster())
        broker.query(_sql())  # warm
        srv = QueryServer(broker).start()
        try:
            code, payload = self._post(
                srv.port,
                {"sql": "SET allowPartialResults = true; SET maxRuntimeMs = 0.001; " + _sql()},
            )
            assert code == 200
            assert payload["partialResult"] is True
            assert any(
                e.get("errorCode") == "QUERY_KILLED" for e in payload["exceptions"]
            )
        finally:
            srv.stop()

    def test_debug_admission_snapshot(self):
        from pinot_tpu.cluster.rest import QueryServer

        broker = Broker(_cluster())
        srv = QueryServer(broker).start()
        try:
            code, payload = self._get(srv.port, "/debug/admission")
            assert code == 200
            assert set(payload) >= {"pressureLevel", "admission", "hostBudget", "watchdog"}
            assert payload["hostBudget"]["budgetBytes"] > 0
        finally:
            srv.stop()
