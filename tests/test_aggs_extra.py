"""Extended aggregation tests: log-sketch percentile on heavy tails, theta
distinct count, MODE, FIRST/LAST_WITH_TIME.

Reference model: PercentileKLLAggregationFunction (error-bounded quantiles
on skewed data), DistinctCountThetaSketchAggregationFunction,
ModeAggregationFunction, Last/FirstWithTimeAggregationFunction.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

N = 50_000


def _make_engine(data, schema, n_segments=3):
    eng = QueryEngine()
    eng.register_table(schema)
    n = len(next(iter(data.values())))
    bounds = np.linspace(0, n, n_segments + 1).astype(int)
    for i in range(n_segments):
        chunk = {k: v[bounds[i] : bounds[i + 1]] for k, v in data.items()}
        eng.add_segment(schema.name, build_segment(schema, chunk, f"s{i}"))
    return eng


class TestLogSketchPercentile:
    @pytest.fixture(scope="class")
    def heavy(self):
        rng = np.random.default_rng(31)
        # lognormal with sigma=3: spans ~9 orders of magnitude; an equi-width
        # histogram puts essentially all mass in bin 0
        vals = rng.lognormal(mean=2.0, sigma=3.0, size=N)
        schema = Schema("h", [FieldSpec("v", DataType.DOUBLE, role=FieldRole.METRIC)])
        return _make_engine({"v": vals}, schema), vals

    @pytest.mark.parametrize("rank", [50, 95, 99])
    def test_relative_error_on_heavy_tail(self, heavy, rank):
        eng, vals = heavy
        res = eng.query(f"SELECT PERCENTILEKLL(v, {rank}) FROM h")
        got = float(res.rows[0][0])
        true = float(np.percentile(vals, rank))
        rel = abs(got - true) / true
        assert rel < 0.03, f"p{rank}: got {got}, true {true}, rel err {rel:.4f}"

    def test_histogram_standin_fails_where_logsketch_works(self, heavy):
        """The round-2 equi-width histogram visibly fails on this data —
        the finding that motivated the real sketch (VERDICT r2 #9)."""
        eng, vals = heavy
        true = float(np.percentile(vals, 50))
        hist = float(eng.query("SELECT PERCENTILETDIGEST(v, 50) FROM h").rows[0][0])
        log = float(eng.query("SELECT PERCENTILEKLL(v, 50) FROM h").rows[0][0])
        assert abs(log - true) / true < 0.03
        assert abs(hist - true) / true > 0.5  # equi-width is off by >50% here

    def test_negative_and_zero_values(self):
        rng = np.random.default_rng(5)
        vals = np.concatenate([-rng.lognormal(1, 2, 20000), np.zeros(1000), rng.lognormal(1, 2, 20000)])
        schema = Schema("m", [FieldSpec("v", DataType.DOUBLE, role=FieldRole.METRIC)])
        eng = _make_engine({"v": vals}, schema)
        for rank in (10, 50, 90):
            got = float(eng.query(f"SELECT PERCENTILEKLL(v, {rank}) FROM m").rows[0][0])
            true = float(np.percentile(vals, rank))
            denom = max(abs(true), 1e-9)
            assert abs(got - true) / denom < 0.05, (rank, got, true)

    def test_grouped_log_sketch(self):
        rng = np.random.default_rng(7)
        g = rng.integers(0, 4, 20000)
        vals = rng.lognormal(mean=g.astype(float), sigma=2.0)
        schema = Schema(
            "gg",
            [FieldSpec("g", DataType.INT), FieldSpec("v", DataType.DOUBLE, role=FieldRole.METRIC)],
        )
        eng = _make_engine({"g": g, "v": vals}, schema)
        res = eng.query("SELECT g, PERCENTILEKLL(v, 50) FROM gg GROUP BY g ORDER BY g")
        for row in res.rows:
            true = float(np.percentile(vals[g == int(row[0])], 50))
            assert abs(float(row[1]) - true) / true < 0.03


class TestTheta:
    def test_exact_below_k(self):
        rng = np.random.default_rng(11)
        vals = rng.integers(0, 1000, N)  # 1000 distinct < K=4096
        schema = Schema("t", [FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)])
        eng = _make_engine({"v": vals}, schema)
        got = int(eng.query("SELECT DISTINCTCOUNTTHETA(v) FROM t").rows[0][0])
        assert got == len(np.unique(vals))

    def test_estimate_above_k(self):
        rng = np.random.default_rng(13)
        vals = rng.integers(0, 40_000, 200_000)
        true = len(np.unique(vals))
        schema = Schema("t", [FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)])
        eng = _make_engine({"v": vals}, schema, n_segments=4)
        got = float(eng.query("SELECT DISTINCTCOUNTTHETA(v) FROM t").rows[0][0])
        assert abs(got - true) / true < 0.05, (got, true)


class TestMode:
    def test_mode_scalar_and_grouped(self):
        rng = np.random.default_rng(17)
        g = rng.integers(0, 3, 30000)
        # per-group biased distribution: mode of group i is i*10
        v = np.where(rng.random(30000) < 0.4, g * 10, rng.integers(0, 100, 30000))
        schema = Schema(
            "mo",
            [FieldSpec("g", DataType.INT), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)],
        )
        eng = _make_engine({"g": g, "v": v}, schema)
        res = eng.query("SELECT g, MODE(v) FROM mo GROUP BY g ORDER BY g")
        for row in res.rows:
            vg = v[g == int(row[0])]
            counts = np.bincount(vg)
            expected = counts.argmax()  # ties -> smallest, same as MODE
            assert float(row[1]) == float(expected)
        scalar = eng.query("SELECT MODE(v) FROM mo").rows[0][0]
        assert float(scalar) == float(np.bincount(v).argmax())


class TestFirstLastWithTime:
    @pytest.fixture(scope="class")
    def env(self):
        rng = np.random.default_rng(19)
        n = 20000
        g = rng.integers(0, 5, n)
        t = rng.permutation(n).astype(np.int64) + 1_000_000
        v = rng.integers(0, 10_000, n)
        schema = Schema(
            "lt",
            [
                FieldSpec("g", DataType.INT),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("t", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
        )
        return _make_engine({"g": g, "v": v, "t": t}, schema), g, t, v

    def test_last_with_time_scalar(self, env):
        eng, g, t, v = env
        got = eng.query("SELECT LASTWITHTIME(v, t, 'LONG') FROM lt").rows[0][0]
        assert float(got) == float(v[np.argmax(t)])

    def test_first_with_time_scalar(self, env):
        eng, g, t, v = env
        got = eng.query("SELECT FIRSTWITHTIME(v, t, 'LONG') FROM lt").rows[0][0]
        assert float(got) == float(v[np.argmin(t)])

    def test_last_with_time_grouped(self, env):
        eng, g, t, v = env
        res = eng.query("SELECT g, LASTWITHTIME(v, t, 'LONG'), FIRSTWITHTIME(v, t, 'LONG') FROM lt GROUP BY g ORDER BY g")
        for row in res.rows:
            m = g == int(row[0])
            assert float(row[1]) == float(v[m][np.argmax(t[m])])
            assert float(row[2]) == float(v[m][np.argmin(t[m])])

    def test_last_with_filter(self, env):
        eng, g, t, v = env
        got = eng.query("SELECT LASTWITHTIME(v, t, 'LONG') FROM lt WHERE g = 2").rows[0][0]
        m = g == 2
        assert float(got) == float(v[m][np.argmax(t[m])])


class TestDistinctSumAvg:
    def test_distinctsum_distinctavg(self):
        rng = np.random.default_rng(23)
        v = rng.integers(0, 200, 20000)
        g = rng.integers(0, 3, 20000)
        schema = Schema(
            "ds",
            [FieldSpec("g", DataType.INT), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)],
        )
        eng = _make_engine({"g": g, "v": v}, schema)
        res = eng.query("SELECT DISTINCTSUM(v), DISTINCTAVG(v) FROM ds")
        distinct = np.unique(v)
        assert float(res.rows[0][0]) == float(distinct.sum())
        assert abs(float(res.rows[0][1]) - float(distinct.mean())) < 1e-9
        res2 = eng.query("SELECT g, DISTINCTSUM(v) FROM ds GROUP BY g ORDER BY g")
        for row in res2.rows:
            d = np.unique(v[g == int(row[0])])
            assert float(row[1]) == float(d.sum())


class TestGroupedTheta:
    def test_grouped_theta_exact_below_k(self):
        rng = np.random.default_rng(29)
        n = 60_000
        g = rng.integers(0, 5, n)
        # ~120 distinct values per group << per-group K
        v = rng.integers(0, 120, n) + g * 1000
        schema = Schema(
            "gt",
            [FieldSpec("g", DataType.INT), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)],
        )
        eng = _make_engine({"g": g, "v": v}, schema)
        res = eng.query("SELECT g, DISTINCTCOUNTTHETA(v) FROM gt GROUP BY g ORDER BY g")
        for row in res.rows:
            expected = len(np.unique(v[g == int(row[0])]))
            assert int(row[1]) == expected, (row, expected)

    def test_grouped_theta_estimates_above_k(self):
        rng = np.random.default_rng(31)
        n = 200_000
        g = rng.integers(0, 4, n)
        v = rng.integers(0, 5000, n) + g * 100_000  # ~5000 distinct per group > K=256
        schema = Schema(
            "gt2",
            [FieldSpec("g", DataType.INT), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)],
        )
        eng = _make_engine({"g": g, "v": v}, schema, n_segments=3)
        res = eng.query("SELECT g, DISTINCTCOUNTTHETA(v) FROM gt2 GROUP BY g ORDER BY g")
        for row in res.rows:
            true = len(np.unique(v[g == int(row[0])]))
            rel = abs(float(row[1]) - true) / true
            assert rel < 0.15, (row, true, rel)  # K=256 -> ~6% typical error

    def test_small_segment_does_not_cap_accuracy(self):
        """A tiny segment must not shrink the merged sketch width
        (review-caught: exactness below K has to survive the union)."""
        rng = np.random.default_rng(41)
        v = np.concatenate([rng.integers(0, 200, 30), rng.integers(100, 400, 50_000)])
        schema = Schema("tt", [FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)])
        eng = QueryEngine()
        eng.register_table(schema)
        eng.add_segment("tt", build_segment(schema, {"v": v[:30]}, "tiny"))
        eng.add_segment("tt", build_segment(schema, {"v": v[30:]}, "big"))
        got = int(eng.query("SELECT DISTINCTCOUNTTHETA(v) FROM tt").rows[0][0])
        assert got == len(np.unique(v))  # still exact: union << K=4096


class TestThetaSetExpressions:
    def test_intersect_union_diff(self):
        rng = np.random.default_rng(43)
        n = 40_000
        user = rng.integers(0, 800, n)
        dim = rng.choice(["a", "b", "c"], n)
        schema = Schema(
            "ts",
            [FieldSpec("dim", DataType.STRING), FieldSpec("user", DataType.LONG, role=FieldRole.METRIC)],
        )
        eng = _make_engine({"dim": dim.astype(object), "user": user}, schema)
        ua = set(user[dim == "a"].tolist())
        ub = set(user[dim == "b"].tolist())
        q = (
            "SELECT DISTINCTCOUNTTHETA(user, 'dim = ''a''', 'dim = ''b''', '{expr}') FROM ts"
        )
        got_i = int(eng.query(q.format(expr="SET_INTERSECT($1, $2)")).rows[0][0])
        assert got_i == len(ua & ub)  # < K -> exact
        got_u = int(eng.query(q.format(expr="SET_UNION($1, $2)")).rows[0][0])
        assert got_u == len(ua | ub)
        got_d = int(eng.query(q.format(expr="SET_DIFF($1, $2)")).rows[0][0])
        assert got_d == len(ua - ub)

    def test_nested_set_expression(self):
        rng = np.random.default_rng(47)
        n = 30_000
        user = rng.integers(0, 500, n)
        dim = rng.choice(["a", "b", "c"], n)
        schema = Schema(
            "ts2",
            [FieldSpec("dim", DataType.STRING), FieldSpec("user", DataType.LONG, role=FieldRole.METRIC)],
        )
        eng = _make_engine({"dim": dim.astype(object), "user": user}, schema)
        ua = set(user[dim == "a"].tolist())
        ub = set(user[dim == "b"].tolist())
        uc = set(user[dim == "c"].tolist())
        got = int(
            eng.query(
                "SELECT DISTINCTCOUNTTHETA(user, 'dim = ''a''', 'dim = ''b''', 'dim = ''c''', "
                "'SET_INTERSECT(SET_UNION($1, $2), $3)') FROM ts2"
            ).rows[0][0]
        )
        assert got == len((ua | ub) & uc)

    def test_single_filter_and_dollar_in_literal(self):
        """Review regressions: one sub-filter returns a scalar count, and a
        '$' inside a filter literal is NOT mistaken for a set expression."""
        rng = np.random.default_rng(53)
        dim = rng.choice(["a$b", "c"], 2000)
        user = rng.integers(0, 300, 2000)
        schema = Schema(
            "tdollar",
            [FieldSpec("dim", DataType.STRING), FieldSpec("user", DataType.LONG, role=FieldRole.METRIC)],
        )
        eng = _make_engine({"dim": dim.astype(object), "user": user}, schema)
        got = eng.query("SELECT DISTINCTCOUNTTHETA(user, 'dim = ''a$b''') FROM tdollar").rows[0][0]
        assert int(got) == len(set(user[dim == "a$b"].tolist()))


class TestFrequentLongs:
    def test_top_k_values(self):
        rng = np.random.default_rng(59)
        # zipf-ish: value i appears ~ (20 - i) * 100 times
        parts = [np.full((20 - i) * 100, i) for i in range(20)]
        v = np.concatenate(parts)
        rng.shuffle(v)
        g = rng.integers(0, 2, len(v))
        schema = Schema(
            "fl",
            [FieldSpec("g", DataType.INT), FieldSpec("v", DataType.LONG, role=FieldRole.METRIC)],
        )
        eng = _make_engine({"g": g, "v": v}, schema)
        got = eng.query("SELECT FREQUENTLONGS(v, 5) FROM fl").rows[0][0]
        assert got == [0, 1, 2, 3, 4]  # exact global frequency order
        res = eng.query("SELECT g, FREQUENTLONGS(v, 3) FROM fl GROUP BY g ORDER BY g")
        for row in res.rows:
            vg = v[g == int(row[0])]
            counts = np.bincount(vg)
            expected = list(np.argsort(-counts, kind="stable")[:3])
            assert row[1] == [int(x) for x in expected]
