"""Observability surface: trace trees under injected faults, latency
histograms, Prometheus exposition, the slow-query log and EXPLAIN ANALYZE.

The trace assertions pin the PR's acceptance shape: one span tree per query
with the broker scatter, per-round failover, per-server execute (grafted
server subtree with dispatch/device_wait/collect) all visible, durations
non-zero where work happened.
"""
import json
import threading
import urllib.request

import numpy as np
import pytest

from pinot_tpu.cluster import Broker, Coordinator, FaultPlan, ServerInstance
from pinot_tpu.cluster.rest import QueryServer
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.utils.metrics import METRICS, Histogram, MetricsRegistry
from pinot_tpu.utils.slowlog import SlowQueryLog


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )


def _data(n, seed):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "v": rng.integers(0, 100, n),
    }


def _engine(n_segments=3, rows=200):
    eng = QueryEngine()
    eng.register_table(_schema())
    for i in range(n_segments):
        eng.add_segment("t", build_segment(_schema(), _data(rows, 100 + i), f"seg{i}"))
    return eng


def _cluster(n_servers=2, replication=2, n_segments=4, rows=200):
    coord = Coordinator(replication=replication)
    for i in range(n_servers):
        coord.register_server(ServerInstance(f"server{i}"))
    coord.add_table(_schema(), TableConfig(name="t"))
    for i in range(n_segments):
        coord.add_segment("t", build_segment(_schema(), _data(rows, 100 + i), f"seg{i}"))
    return coord


def _spans(node, out=None):
    """Flatten a span tree into {name: [node, ...]}."""
    if out is None:
        out = {}
    out.setdefault(node["name"], []).append(node)
    for c in node.get("children", []):
        _spans(c, out)
    return out


# ---------------------------------------------------------------------------
# Histogram + registry
# ---------------------------------------------------------------------------
class TestHistogram:
    def test_quantiles_bracket_the_data(self):
        h = Histogram()
        for ms in range(1, 101):  # 1..100 ms, ~uniform
            h.update(float(ms))
        s = h._snap()
        assert s["count"] == 100
        assert s["minMs"] == 1.0 and s["maxMs"] == 100.0
        # log-bucketed interpolation: a few percent of bucket width
        assert 30 <= s["p50Ms"] <= 70
        assert 75 <= s["p95Ms"] <= 100
        assert s["p95Ms"] <= s["p99Ms"] <= 100

    def test_buckets_are_cumulative_and_end_at_inf(self):
        h = Histogram()
        for ms in (0.05, 1.0, 10.0, 1e9):  # below first bound + overflow
            h.update(ms)
        b = h.buckets()
        assert b[-1][0] == float("inf") and b[-1][1] == 4
        counts = [c for _, c in b]
        assert counts == sorted(counts), "bucket counts must be cumulative"

    def test_empty_histogram_snapshots_zeros(self):
        s = Histogram()._snap()
        assert s == {
            "count": 0, "meanMs": 0.0, "maxMs": 0.0, "minMs": 0.0,
            "p50Ms": 0.0, "p95Ms": 0.0, "p99Ms": 0.0,
        }

    def test_concurrent_updates_are_exact(self):
        reg = MetricsRegistry()
        n, threads = 2000, 8

        def work():
            for i in range(n):
                reg.counter("c").inc()
                reg.histogram("h").update(float(i % 50))
                reg.gauge("g").add(1.0)

        ts = [threading.Thread(target=work) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        snap = reg.snapshot()
        assert snap["counters"]["c"] == n * threads
        assert snap["histograms"]["h"]["count"] == n * threads
        assert snap["gauges"]["g"] == float(n * threads)

    def test_snapshot_during_concurrent_registration(self):
        reg = MetricsRegistry()
        stop = threading.Event()
        errors = []

        def register():
            i = 0
            while not stop.is_set():
                reg.counter(f"series{i % 500}").inc()
                i += 1

        def snap():
            try:
                for _ in range(200):
                    reg.snapshot()
                    reg.to_prometheus()
            except Exception as e:  # pragma: no cover - the failure under test
                errors.append(e)

        reg_t = threading.Thread(target=register)
        snap_t = threading.Thread(target=snap)
        reg_t.start()
        snap_t.start()
        snap_t.join()
        stop.set()
        reg_t.join()
        assert errors == []


class TestPrometheusExposition:
    def test_counter_gauge_histogram_render(self):
        reg = MetricsRegistry()
        reg.counter("broker.queries").inc(3)
        reg.gauge("broker.openBreakers").set(1)
        reg.timer("plan").update(2.0)
        for ms in (0.5, 5.0, 500.0):
            reg.histogram("queryLatency").update(ms)
        text = reg.to_prometheus()
        lines = text.splitlines()
        assert "pinot_broker_queries_total 3" in lines
        assert "pinot_broker_openBreakers 1" in lines
        assert "# TYPE pinot_queryLatency_ms histogram" in lines
        assert 'pinot_queryLatency_ms_bucket{le="+Inf"} 3' in lines
        assert "pinot_queryLatency_ms_count 3" in lines
        assert any(l.startswith("pinot_queryLatency_ms_sum ") for l in lines)
        assert "pinot_plan_ms_count 1" in lines
        # bucket series are monotone non-decreasing
        cums = [
            int(l.rsplit(" ", 1)[1])
            for l in lines
            if l.startswith("pinot_queryLatency_ms_bucket")
        ]
        assert cums == sorted(cums)

    def test_names_are_sanitized(self):
        reg = MetricsRegistry()
        reg.counter("server.segmentBytes.my-table").inc()
        assert "pinot_server_segmentBytes_my_table_total 1" in reg.to_prometheus()


# ---------------------------------------------------------------------------
# Trace propagation
# ---------------------------------------------------------------------------
class TestEngineTrace:
    def test_device_host_split_spans(self):
        eng = _engine()
        res = eng.query("SET trace = true; SELECT city, COUNT(*) FROM t GROUP BY city")
        spans = _spans(res.stats.trace)
        assert res.stats.query_id and res.stats.query_id.startswith("engine_")
        assert spans["query"][0]["attrs"]["queryId"] == res.stats.query_id
        dw = spans["device_wait"][0]
        assert dw["attrs"]["launches"] == 3
        assert len([n for n in spans if n.startswith("launch:")]) == 3
        assert len(spans["collect"]) == 3

    def test_untraced_query_has_no_id_overhead_fields(self):
        eng = _engine()
        res = eng.query("SELECT COUNT(*) FROM t")
        assert res.stats.trace is None
        assert res.stats.query_id is not None  # id minted regardless


class TestBrokerFaultTrace:
    def test_single_tree_with_failover_rounds(self):
        """One server killed mid-scatter: the finished trace is ONE tree
        holding the broker scatter, both rounds, the failed server_execute
        (error + breaker state) and each surviving server's grafted subtree
        with dispatch/device_wait/collect spans."""
        coord = _cluster()
        FaultPlan(seed=7).fail_server("server0", on_call=1).attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        res = broker.query("SET trace = true; SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city")
        tr = res.stats.trace
        assert tr["name"] == "query"
        assert tr["attrs"]["queryId"] == res.stats.query_id
        spans = _spans(tr)
        assert "scatter" in spans and "round:0" in spans and "round:1" in spans
        execs = spans["server_execute"]
        failed = [s for s in execs if "error" in s.get("attrs", {})]
        assert len(failed) == 1
        assert failed[0]["attrs"]["server"] == "server0"
        assert "breaker" in failed[0]["attrs"]
        # surviving calls graft the server-built subtree under themselves
        ok = [s for s in execs if "error" not in s.get("attrs", {})]
        assert ok, "at least one server call must succeed"
        for s in ok:
            sub = [c for c in s.get("children", []) if c["name"].startswith("server:")]
            assert len(sub) == 1
            sub_spans = _spans(sub[0])
            assert "dispatch" in sub_spans
            assert "device_wait" in sub_spans
            assert "collect" in sub_spans
            assert sub[0]["attrs"]["backend"]
            assert sub[0]["ms"] > 0
        assert spans["dispatch"][0]["ms"] > 0
        assert tr["ms"] > 0

    def test_breaker_and_inflight_gauges_published(self):
        coord = _cluster()
        FaultPlan(seed=7).fail_server("server0", on_call=1).attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        broker.query("SELECT COUNT(*) FROM t")
        snap = METRICS.snapshot()
        assert "broker.openBreakers" in snap["gauges"]
        assert "broker.breakerOpen.server0" in snap["gauges"]
        assert snap["gauges"]["broker.inFlightScatters"] == 0.0
        assert snap["histograms"]["broker.queryLatency"]["count"] == 1
        assert snap["gauges"]["server.segmentBytes.t"] > 0


# ---------------------------------------------------------------------------
# Slow-query log
# ---------------------------------------------------------------------------
class TestSlowQueryLog:
    def test_ring_evicts_oldest(self):
        log = SlowQueryLog(capacity=4, slow_ms=1e12)
        for i in range(10):
            log.record(f"SELECT {i}", f"fp{i}")
        snap = log.snapshot()
        assert len(log) == 4 and len(snap) == 4
        assert [e["sql"] for e in snap] == ["SELECT 9", "SELECT 8", "SELECT 7", "SELECT 6"]

    def test_trace_kept_only_over_threshold(self):
        log = SlowQueryLog(capacity=8, slow_ms=50.0)

        class R:
            rows = [(1,)]

            class stats:
                time_ms = 0.0
                query_id = "q"
                num_docs_scanned = 1
                num_segments_processed = 1
                partial_result = False
                exceptions = []
                trace = {"name": "query", "ms": 1.0}

        R.stats.time_ms = 10.0
        fast = log.record("SELECT 1", "fp", R)
        R.stats.time_ms = 90.0
        slow = log.record("SELECT 2", "fp", R)
        assert "trace" not in fast and slow["trace"]["name"] == "query"

    def test_errors_are_logged_and_counted(self):
        eng = _engine()
        with pytest.raises(Exception):
            eng.query("SELECT nope FROM t")
        e = eng.slow_queries.snapshot(1)[0]
        assert "error" in e and "nope" in e["sql"]
        assert METRICS.snapshot()["counters"]["broker.slowQueries"] >= 1

    def test_engine_records_every_query_newest_first(self):
        eng = _engine()
        eng.query("SELECT COUNT(*) FROM t")
        eng.query("SELECT SUM(v) FROM t")
        snap = eng.slow_queries.snapshot()
        assert len(snap) == 2
        assert "SUM" in snap[0]["sql"]  # newest first
        assert snap[0]["queryId"].startswith("engine_")
        assert snap[0]["rows"] == 1 and snap[0]["numDocsScanned"] > 0


# ---------------------------------------------------------------------------
# REST + CLI surface
# ---------------------------------------------------------------------------
class TestRestSurface:
    @pytest.fixture()
    def server(self):
        srv = QueryServer(_engine()).start()
        yield srv
        srv.stop()

    def _get(self, srv, path):
        with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}{path}") as r:
            return r.headers.get("Content-Type", ""), r.read().decode("utf-8")

    def _post(self, srv, sql):
        body = json.dumps({"sql": sql}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/query/sql", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read().decode("utf-8"))

    def test_prometheus_format_and_json_default(self, server):
        self._post(server, "SELECT COUNT(*) FROM t")
        ctype, text = self._get(server, "/metrics?format=prometheus")
        assert ctype.startswith("text/plain")
        assert "pinot_queries_total" in text
        assert 'pinot_queryLatency_ms_bucket{le="+Inf"} 1' in text
        ctype, body = self._get(server, "/metrics")
        assert ctype.startswith("application/json")
        snap = json.loads(body)
        assert "counters" in snap and "histograms" in snap

    def test_debug_queries_and_request_id(self, server):
        resp = self._post(server, "SELECT COUNT(*) FROM t")
        assert resp["requestId"].startswith("engine_")
        _, body = self._get(server, "/debug/queries?limit=5")
        entries = json.loads(body)["queries"]
        assert entries and entries[0]["queryId"] == resp["requestId"]

    def test_cli_slow_queries(self, server, capsys):
        from pinot_tpu.tools.cli import main

        self._post(server, "SELECT city, COUNT(*) FROM t GROUP BY city")
        rc = main(["slow-queries", "--url", f"http://127.0.0.1:{server.port}", "--limit", "3"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "GROUP BY city" in out and "qid=engine_" in out
        rc = main(["slow-queries", "--url", f"http://127.0.0.1:{server.port}", "--json"])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE
# ---------------------------------------------------------------------------
class TestExplainAnalyze:
    def test_engine_operator_rows_join_measured_ms(self):
        eng = _engine()
        res = eng.query("EXPLAIN ANALYZE SELECT city, SUM(v) FROM t WHERE city = 'sf' GROUP BY city")
        assert res.columns == [
            "Operator", "Operator_Id", "Parent_Id", "Actual_Ms", "Rows",
            "Bytes", "Flops", "Roofline_Pct",
        ]
        by_op = {r[0].split("(")[0]: r for r in res.rows if not r[0].startswith("TRACE")}
        assert by_op["BROKER_REDUCE"][3] is not None and by_op["BROKER_REDUCE"][3] >= 0
        assert by_op["GROUP_BY"][3] is not None and by_op["GROUP_BY"][3] > 0
        assert by_op["FILTER_SCAN"][4] == res.stats.num_docs_scanned
        trace_rows = [r for r in res.rows if r[0].startswith("TRACE")]
        assert trace_rows, "measured span tree must follow the operator rows"
        assert trace_rows[0][2] == 0  # trace root parented at the table root
        assert any("device_wait" in r[0] for r in trace_rows)
        # ids are unique and parents resolve
        ids = [r[1] for r in res.rows]
        assert len(ids) == len(set(ids))
        assert all(r[2] in set(ids) | {0} for r in res.rows)

    def test_broker_explain_analyze_executes_with_trace(self):
        broker = Broker(_cluster())
        res = broker.query("EXPLAIN ANALYZE SELECT COUNT(*) FROM t")
        assert res.columns[3] == "Actual_Ms"
        trace_rows = [r for r in res.rows if r[0].startswith("TRACE")]
        assert any("server_execute" in r[0] for r in trace_rows)
        assert any("scatter" in r[0] for r in trace_rows)
        assert res.stats.num_docs_scanned > 0  # it really executed

    def test_explain_plan_for_still_static(self):
        eng = _engine()
        res = eng.query("EXPLAIN PLAN FOR SELECT COUNT(*) FROM t")
        assert res.columns == ["Operator", "Operator_Id", "Parent_Id"]
        assert METRICS.snapshot()["counters"].get("docsScanned", 0) == 0

    def test_explain_garbage_still_fails(self):
        from pinot_tpu.sql.parser import SqlParseError

        eng = _engine()
        with pytest.raises(SqlParseError):
            eng.query("EXPLAIN NONSENSE SELECT COUNT(*) FROM t")
