"""Interprocedural engine (pinot_tpu.analysis.engine + callgraph):
project model from in-memory sources, call resolution, reachability,
inline suppressions across old and new rules, and baseline matching."""
import textwrap

from pinot_tpu.analysis.callgraph import CallGraph
from pinot_tpu.analysis.engine import (
    Project,
    apply_baseline,
    run_passes,
)
from pinot_tpu.analysis.races import RacePass
from pinot_tpu.analysis.repo_lint import Finding, lint_source


def _project(**files):
    return Project.from_sources(
        {f"pkg/{name.replace('__', '/')}.py": textwrap.dedent(src) for name, src in files.items()}
    )


class TestProjectModel:
    def test_indexes_modules_functions_and_methods(self):
        proj = _project(
            a__b="""
            def top():
                pass

            class C:
                def m(self):
                    pass
            """
        )
        # "a__b" -> relpath pkg/a/b.py -> module pkg.a.b
        assert "pkg.a.b" in proj.modules
        assert "pkg.a.b.top" in proj.functions
        assert "pkg.a.b.C" in proj.classes
        assert "pkg.a.b.C.m" in proj.functions
        assert proj.functions["pkg.a.b.C.m"].cls is proj.classes["pkg.a.b.C"]

    def test_dunder_init_maps_to_package_name(self):
        proj = Project.from_sources({"pkg/sub/__init__.py": "def boot():\n    pass\n"})
        assert "pkg.sub" in proj.modules
        assert "pkg.sub.boot" in proj.functions

    def test_syntax_error_module_is_skipped_not_fatal(self):
        proj = Project.from_sources({"pkg/bad.py": "def broken(:\n", "pkg/ok.py": "x = 1\n"})
        assert "pkg.ok" in proj.modules and "pkg.bad" not in proj.modules

    def test_threading_import_marks_module_threaded(self):
        proj = _project(
            hot="import threading\n",
            cold="import json\n",
            aliased="from threading import Lock\n",
        )
        assert proj.modules["pkg.hot"].threaded
        assert proj.modules["pkg.aliased"].threaded
        assert not proj.modules["pkg.cold"].threaded


class TestResolution:
    def test_resolves_self_method_local_function_and_external(self):
        proj = _project(
            m="""
            import time
            from pkg.util import helper

            def local():
                pass

            class C:
                def a(self):
                    self.b()
                    local()
                    helper()
                    time.sleep(1)

                def b(self):
                    pass
            """,
            util="""
            def helper():
                pass
            """,
        )
        import ast

        fi = proj.functions["pkg.m.C.a"]
        calls = [n for n in ast.walk(fi.node) if isinstance(n, ast.Call)]
        targets = {proj.resolve_call(fi, c) for c in calls}
        assert targets == {"pkg.m.C.b", "pkg.m.local", "pkg.util.helper", "time.sleep"}

    def test_resolves_inherited_method_through_base(self):
        proj = _project(
            m="""
            class Base:
                def shared(self):
                    pass

            class Child(Base):
                def go(self):
                    self.shared()
            """
        )
        import ast

        fi = proj.functions["pkg.m.Child.go"]
        call = next(n for n in ast.walk(fi.node) if isinstance(n, ast.Call))
        assert proj.resolve_call(fi, call) == "pkg.m.Base.shared"


class TestCallGraph:
    def test_edges_external_and_reachability(self):
        proj = _project(
            m="""
            import time

            def entry():
                middle()

            def middle():
                time.sleep(1)

            def orphan():
                pass
            """
        )
        g = CallGraph.build(proj)
        assert "pkg.m.middle" in g.callees("pkg.m.entry")
        assert "time.sleep" in g.external.get("pkg.m.middle", {})
        reach = g.reachable_from(["pkg.m.entry"])
        assert "pkg.m.middle" in reach and "pkg.m.orphan" not in reach

    def test_instantiation_reaches_init(self):
        proj = _project(
            m="""
            class C:
                def __init__(self):
                    pass

            def make():
                return C()
            """
        )
        g = CallGraph.build(proj)
        assert "pkg.m.C.__init__" in g.callees("pkg.m.make")


class TestInlineSuppression:
    def test_per_file_rule_honors_disable_comment(self):
        src = textwrap.dedent(
            """
            class Broker:
                def route(self):
                    self._rr += 1  # pinot-lint: disable=W004
            """
        )
        assert lint_source(src, path="cluster/b.py", threaded=True) == []

    def test_disable_all_and_wrong_rule_spec(self):
        base = "class B:\n    def r(self):\n        self._rr += 1{}\n"
        assert lint_source(base.format("  # pinot-lint: disable=all"), "c/b.py", threaded=True) == []
        kept = lint_source(base.format("  # pinot-lint: disable=W001"), "c/b.py", threaded=True)
        assert [f.rule for f in kept] == ["W004"]

    def test_interprocedural_rule_honors_disable_comment(self):
        src = """
        import threading

        class Stats:
            def __init__(self):
                self._lock = threading.Lock()
                self._total = 0

            def add(self, n):
                with self._lock:
                    self._total += n

            def peek(self):
                return self._total  # pinot-lint: disable=W010
        """
        flagged = run_passes(
            _project(m=src.replace("  # pinot-lint: disable=W010", "")), [RacePass()]
        )
        assert [f.rule for f in flagged] == ["W010"]
        assert run_passes(_project(m=src), [RacePass()]) == []


class TestBaseline:
    def test_matches_by_symbol_and_reports_stale(self):
        findings = [
            Finding("pinot_tpu/x.py", 10, "W010", "a", symbol="C.m"),
            Finding("pinot_tpu/y.py", 20, "W013", "b", symbol="f"),
        ]
        baseline = [
            {"rule": "W010", "path": "pinot_tpu/x.py", "symbol": "C.m", "justification": "ok"},
            {"rule": "W012", "path": "pinot_tpu/gone.py", "symbol": "D.n", "justification": "old"},
        ]
        kept, baselined, stale = apply_baseline(findings, baseline)
        assert [f.rule for f in kept] == ["W013"]
        assert baselined == 1
        assert stale == [baseline[1]]

    def test_symbol_mismatch_does_not_match_even_on_same_line(self):
        findings = [Finding("pinot_tpu/x.py", 10, "W010", "a", symbol="C.m")]
        baseline = [{"rule": "W010", "path": "pinot_tpu/x.py", "symbol": "C.other"}]
        kept, baselined, stale = apply_baseline(findings, baseline)
        assert len(kept) == 1 and baselined == 0 and len(stale) == 1

    def test_line_fallback_when_no_symbol(self):
        findings = [Finding("pinot_tpu/x.py", 10, "W010", "a", symbol="C.m")]
        baseline = [{"rule": "W010", "path": "pinot_tpu/x.py", "line": 10}]
        kept, baselined, _stale = apply_baseline(findings, baseline)
        assert kept == [] and baselined == 1


def test_finding_to_dict_and_hint_rendering():
    f = Finding("a/b.py", 12, "W010", "msg", hint="take the lock", symbol="C.m")
    assert str(f) == "a/b.py:12: W010 msg [fix: take the lock]"
    d = f.to_dict()
    assert d["path"] == "a/b.py" and d["rule"] == "W010" and d["symbol"] == "C.m"
    # no-hint findings keep the legacy greppable format
    assert str(Finding("a/b.py", 12, "W001", "msg")) == "a/b.py:12: W001 msg"
