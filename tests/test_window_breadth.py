"""Window-function breadth: value functions, NTILE, frame generality.

Round-4 verdict missing #1.  Reference parity:
pinot-query-runtime/.../runtime/operator/window/value/
LagValueWindowFunction.java, LeadValueWindowFunction.java,
FirstValueWindowFunction.java, LastValueWindowFunction.java,
range/NtileWindowFunction.java, aggregate window functions under
window/aggregate/, frames per WindowFrame.java.  sqlite implements the
same SQL-standard semantics — direct goldens, including the standard
default frame (RANGE UNBOUNDED PRECEDING..CURRENT ROW when ORDER BY is
present).
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data

N = 3000


def _schema(name="t"):
    return Schema(
        name,
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("dept", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("score", DataType.DOUBLE, role=FieldRole.METRIC),
        ],
    )


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(77)
    data = {
        "city": rng.choice(["sf", "nyc", "la"], N).astype(object),
        "dept": rng.choice(["eng", "ops", "biz"], N).astype(object),
        "v": rng.integers(0, 100_000, N),  # near-unique order key
        "score": np.round(rng.random(N) * 100, 3),
    }
    eng = QueryEngine()
    eng.register_table(_schema())
    for i, sl in enumerate([slice(0, N // 2), slice(N // 2, N)]):
        chunk = {k: val[sl] for k, val in data.items()}
        eng.add_segment("t", build_segment(_schema(), chunk, f"s{i}"))
    conn = sqlite_from_data("t", data)
    return eng, conn


def _golden(env, sql):
    eng, conn = env
    assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)


class TestValueFunctions:
    def test_lag_default_offset(self, env):
        _golden(env, (
            "SELECT city, v, LAG(v) OVER (PARTITION BY city ORDER BY v) "
            "FROM t WHERE v < 3000 ORDER BY city, v LIMIT 120"
        ))

    def test_lag_offset_and_default(self, env):
        _golden(env, (
            "SELECT city, v, LAG(v, 3, -1) OVER (PARTITION BY city ORDER BY v) "
            "FROM t WHERE v < 3000 ORDER BY city, v LIMIT 120"
        ))

    def test_lead(self, env):
        _golden(env, (
            "SELECT dept, v, LEAD(v, 2) OVER (PARTITION BY dept ORDER BY v DESC) "
            "FROM t WHERE v > 97000 ORDER BY dept, v DESC LIMIT 120"
        ))

    def test_lag_string_values(self, env):
        _golden(env, (
            "SELECT v, dept, LAG(dept) OVER (ORDER BY v) "
            "FROM t WHERE v < 1500 ORDER BY v LIMIT 80"
        ))

    def test_first_last_value_default_frame(self, env):
        # default frame with ORDER BY: LAST_VALUE ends at the peer group
        _golden(env, (
            "SELECT city, v, FIRST_VALUE(v) OVER (PARTITION BY city ORDER BY v), "
            "LAST_VALUE(v) OVER (PARTITION BY city ORDER BY v) "
            "FROM t WHERE v < 3000 ORDER BY city, v LIMIT 120"
        ))

    def test_last_value_whole_partition_frame(self, env):
        _golden(env, (
            "SELECT city, v, LAST_VALUE(v) OVER (PARTITION BY city ORDER BY v "
            "ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) "
            "FROM t WHERE v < 3000 ORDER BY city, v LIMIT 120"
        ))


class TestNtile:
    @pytest.mark.parametrize("t", [2, 3, 7])
    def test_ntile(self, env, t):
        _golden(env, (
            f"SELECT city, v, NTILE({t}) OVER (PARTITION BY city ORDER BY v) "
            "FROM t WHERE v < 4000 ORDER BY city, v LIMIT 150"
        ))

    def test_ntile_more_buckets_than_rows(self, env):
        _golden(env, (
            "SELECT city, v, NTILE(500) OVER (PARTITION BY city ORDER BY v) "
            "FROM t WHERE v < 500 ORDER BY city, v LIMIT 60"
        ))


class TestFrameAggregates:
    def test_default_frame_cumulative_sum(self, env):
        # SQL default with ORDER BY = RANGE UNBOUNDED..CURRENT (peer-aware)
        _golden(env, (
            "SELECT city, v, SUM(v) OVER (PARTITION BY city ORDER BY v), "
            "AVG(v) OVER (PARTITION BY city ORDER BY v) "
            "FROM t WHERE v < 3000 ORDER BY city, v LIMIT 120"
        ))

    def test_rows_sliding_sum_count(self, env):
        _golden(env, (
            "SELECT city, v, "
            "SUM(v) OVER (PARTITION BY city ORDER BY v ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING), "
            "COUNT(*) OVER (PARTITION BY city ORDER BY v ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING) "
            "FROM t WHERE v < 3000 ORDER BY city, v LIMIT 120"
        ))

    def test_rows_min_max_sliding(self, env):
        _golden(env, (
            "SELECT dept, v, "
            "MIN(v) OVER (PARTITION BY dept ORDER BY v ROWS BETWEEN 3 PRECEDING AND 3 FOLLOWING), "
            "MAX(v) OVER (PARTITION BY dept ORDER BY v ROWS BETWEEN 3 PRECEDING AND 3 FOLLOWING) "
            "FROM t WHERE v < 3000 ORDER BY dept, v LIMIT 120"
        ))

    def test_rows_max_cumulative(self, env):
        _golden(env, (
            "SELECT dept, v, score, "
            "MAX(score) OVER (PARTITION BY dept ORDER BY v ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) "
            "FROM t WHERE v > 96000 ORDER BY dept, v LIMIT 120"
        ))

    def test_rows_suffix_frame(self, env):
        _golden(env, (
            "SELECT city, v, "
            "SUM(v) OVER (PARTITION BY city ORDER BY v ROWS BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING), "
            "MIN(v) OVER (PARTITION BY city ORDER BY v ROWS BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) "
            "FROM t WHERE v < 3000 ORDER BY city, v LIMIT 120"
        ))

    def test_rows_following_only_frame_empty_at_end(self, env):
        # frame entirely ahead of the current row: empty near partition end
        _golden(env, (
            "SELECT city, v, "
            "SUM(v) OVER (PARTITION BY city ORDER BY v ROWS BETWEEN 1 FOLLOWING AND 2 FOLLOWING) "
            "FROM t WHERE v < 2000 ORDER BY city, v LIMIT 100"
        ))

    def test_range_offset_frame(self, env):
        _golden(env, (
            "SELECT city, v, "
            "SUM(v) OVER (PARTITION BY city ORDER BY v RANGE BETWEEN 500 PRECEDING AND 500 FOLLOWING), "
            "COUNT(*) OVER (PARTITION BY city ORDER BY v RANGE BETWEEN 500 PRECEDING AND 500 FOLLOWING) "
            "FROM t WHERE v < 5000 ORDER BY city, v LIMIT 150"
        ))

    def test_range_offset_desc(self, env):
        # descending order: PRECEDING means larger values
        _golden(env, (
            "SELECT city, v, "
            "SUM(v) OVER (PARTITION BY city ORDER BY v DESC RANGE BETWEEN 300 PRECEDING AND CURRENT ROW) "
            "FROM t WHERE v < 4000 ORDER BY city, v DESC LIMIT 150"
        ))

    def test_range_unbounded_to_current_explicit(self, env):
        _golden(env, (
            "SELECT dept, v, "
            "MIN(v) OVER (PARTITION BY dept ORDER BY v RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) "
            "FROM t WHERE v < 3000 ORDER BY dept, v LIMIT 120"
        ))

    def test_count_nonnull_arg(self, env):
        # COUNT(score) over a frame counts non-null rows (all non-null here)
        _golden(env, (
            "SELECT city, v, "
            "COUNT(score) OVER (PARTITION BY city ORDER BY v ROWS BETWEEN 5 PRECEDING AND CURRENT ROW) "
            "FROM t WHERE v < 2000 ORDER BY city, v LIMIT 100"
        ))


class TestFrameValidation:
    def test_ntile_zero_rejected(self, env):
        eng, _ = env
        with pytest.raises(Exception, match="NTILE bucket count"):
            eng.query("SELECT v, NTILE(0) OVER (ORDER BY v) FROM t LIMIT 5")

    def test_inverted_frame_rejected(self, env):
        eng, _ = env
        with pytest.raises(Exception, match="frame start"):
            eng.query(
                "SELECT v, SUM(v) OVER (ORDER BY v ROWS BETWEEN CURRENT ROW AND 2 PRECEDING) "
                "FROM t LIMIT 5"
            )

    def test_shorthand_following_rejected(self, env):
        eng, _ = env
        with pytest.raises(Exception, match="shorthand"):
            eng.query("SELECT v, SUM(v) OVER (ORDER BY v ROWS 3 FOLLOWING) FROM t LIMIT 5")

    def test_range_offset_on_string_key_rejected(self, env):
        eng, _ = env
        with pytest.raises(Exception, match="NUMERIC ORDER BY key"):
            eng.query(
                "SELECT v, SUM(v) OVER (ORDER BY city RANGE BETWEEN 1 PRECEDING AND CURRENT ROW) "
                "FROM t LIMIT 5"
            )


class TestWindowWithNulls:
    def test_sum_skips_nulls_lag_propagates(self):
        rng = np.random.default_rng(5)
        n = 400
        schema = Schema(
            "t",
            [
                FieldSpec("g", DataType.STRING),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("w", DataType.DOUBLE, role=FieldRole.METRIC, nullable=True),
            ],
        )
        w = np.round(rng.random(n) * 10, 2)
        w[rng.random(n) < 0.3] = np.nan
        data = {
            "g": rng.choice(["a", "b"], n).astype(object),
            "v": rng.permutation(n).astype(np.int64),
            "w": w,
        }
        eng = QueryEngine()
        eng.register_table(schema)
        eng.add_segment("t", build_segment(schema, data, "s0"))
        conn = sqlite_from_data("t", data)
        sql = (
            "SELECT g, v, "
            "SUM(w) OVER (PARTITION BY g ORDER BY v ROWS BETWEEN 3 PRECEDING AND CURRENT ROW), "
            "COUNT(w) OVER (PARTITION BY g ORDER BY v ROWS BETWEEN 3 PRECEDING AND CURRENT ROW) "
            "FROM t ORDER BY g, v LIMIT 100"
        )
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall(), ordered=True)
