"""Realtime ingestion tests: consume loop, seal/swap, restart resume.

Reference test model: RealtimeSegmentDataManager consume/commit behavior and
LLC recovery semantics (SURVEY.md §3.3, §4) checked against sqlite goldens.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.realtime import InMemoryStream, RealtimeTableDataManager
from pinot_tpu.realtime.stream import FileStream
from pinot_tpu.spi.config import StreamConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data


def _schema():
    return Schema(
        name="events",
        fields=[
            FieldSpec("city", DataType.STRING),
            FieldSpec("status", DataType.STRING),
            FieldSpec("clicks", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _config(max_rows=40):
    return TableConfig(
        name="events",
        stream=StreamConfig(stream_type="memory", topic="events", max_rows_per_segment=max_rows),
    )


def _rows(n, seed=7):
    rng = np.random.default_rng(seed)
    cities = ["nyc", "sf", "tokyo", "lima"]
    statuses = ["ok", "err"]
    return [
        {
            "city": cities[int(rng.integers(0, len(cities)))],
            "status": statuses[int(rng.integers(0, 2))],
            "clicks": int(rng.integers(0, 100)),
            "ts": 1_700_000_000_000 + i * 1000,
        }
        for i in range(n)
    ]


def _sqlite_for(rows):
    data = {k: np.array([r[k] for r in rows], dtype=object) for k in rows[0]}
    return sqlite_from_data("events", data)


@pytest.fixture()
def engine_with_stream(tmp_path):
    stream = InMemoryStream(num_partitions=2)
    mgr = RealtimeTableDataManager(_schema(), _config(), str(tmp_path / "events"), stream=stream)
    eng = QueryEngine()
    eng.register_table(_schema(), _config())
    eng.attach_realtime("events", mgr)
    return eng, stream, mgr


class TestConsumeAndQuery:
    def test_fresh_rows_visible_before_seal(self, engine_with_stream):
        eng, stream, mgr = engine_with_stream
        rows = _rows(30)  # below the 40-row seal threshold
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        assert mgr.total_rows == 30
        assert not mgr.sealed[0]  # still consuming — rows come from the snapshot
        res = eng.query("SELECT COUNT(*), SUM(clicks) FROM events")
        conn = _sqlite_for(rows)
        assert_same_rows(res.rows, conn.execute("SELECT COUNT(*), SUM(clicks) FROM events").fetchall())

    def test_seal_and_mixed_query(self, engine_with_stream):
        """Rows spanning sealed + consuming segments aggregate consistently."""
        eng, stream, mgr = engine_with_stream
        rows = _rows(100)
        for i, r in enumerate(rows):
            stream.publish(r, partition=i % 2)
        mgr.consume_all()
        # 50 rows per partition, seal at 40 -> 1 sealed + 1 consuming each
        assert len(mgr.sealed[0]) == 1 and len(mgr.sealed[1]) == 1
        assert mgr.total_rows == 100
        conn = _sqlite_for(rows)
        for sql in [
            "SELECT COUNT(*), SUM(clicks), MIN(clicks), MAX(clicks) FROM events",
            "SELECT city, SUM(clicks) FROM events GROUP BY city",
            "SELECT status, COUNT(*) FROM events WHERE clicks > 50 GROUP BY status",
        ]:
            assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_sealed_segment_is_durable_and_indexed(self, engine_with_stream, tmp_path):
        eng, stream, mgr = engine_with_stream
        stream.publish_many(_rows(45), partition=0)
        mgr.consume_all()
        sealed = mgr.sealed[0][0]
        assert sealed.num_docs == 40
        import os

        assert os.path.isdir(mgr.segment_dir(sealed.name))
        # snapshot of the consuming tail holds the remainder
        assert mgr.managers[0].mutable.num_docs == 5


class TestRestartResume:
    def test_restart_resumes_from_committed_offset(self, tmp_path):
        stream = InMemoryStream(num_partitions=1)
        data_dir = str(tmp_path / "events")
        rows = _rows(90)
        mgr = RealtimeTableDataManager(_schema(), _config(), data_dir, stream=stream)
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        assert len(mgr.sealed[0]) == 2  # 90 rows -> two 40-row seals + 10 consuming
        committed_offset = mgr.managers[0].offset
        assert mgr.managers[0].mutable.num_docs == 10

        # "crash": drop the manager; consuming rows are lost by design.
        del mgr
        mgr2 = RealtimeTableDataManager(_schema(), _config(), data_dir, stream=stream)
        # recovery reloaded both sealed segments and resumes at the committed
        # offset (80), NOT at the crashed consumer's in-memory position.
        assert len(mgr2.sealed[0]) == 2
        assert mgr2.managers[0].offset == 80
        assert mgr2.managers[0].seq == 2
        mgr2.consume_all()
        assert mgr2.total_rows == 90  # replayed tail, no dupes, no losses

        eng = QueryEngine()
        eng.register_table(_schema(), _config())
        eng.attach_realtime("events", mgr2)
        conn = _sqlite_for(rows)
        sql = "SELECT city, COUNT(*), SUM(clicks) FROM events GROUP BY city"
        assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_publish_while_consuming_interleaved(self, tmp_path):
        """Queries stay correct as publishes and consume steps interleave."""
        stream = InMemoryStream(num_partitions=1)
        mgr = RealtimeTableDataManager(_schema(), _config(max_rows=25), str(tmp_path / "ev"), stream=stream)
        eng = QueryEngine()
        eng.register_table(_schema(), _config())
        eng.attach_realtime("events", mgr)
        rows = _rows(70)
        seen = []
        for chunk_start in range(0, 70, 10):
            chunk = rows[chunk_start : chunk_start + 10]
            stream.publish_many(chunk, partition=0)
            mgr.consume_all()
            seen.extend(chunk)
            conn = _sqlite_for(seen)
            assert_same_rows(
                eng.query("SELECT COUNT(*), SUM(clicks) FROM events").rows,
                conn.execute("SELECT COUNT(*), SUM(clicks) FROM events").fetchall(),
            )


class TestFileStream:
    def test_jsonl_tail(self, tmp_path):
        import json

        path = str(tmp_path / "in.jsonl")
        rows = _rows(20)
        with open(path, "w") as f:
            for r in rows[:12]:
                f.write(json.dumps(r) + "\n")
        fs = FileStream(path)
        b1 = fs.fetch(0, 8)
        assert len(b1) == 8 and not b1.end_of_partition
        b2 = fs.fetch(b1.next_offset, 100)
        assert len(b2) == 4 and b2.end_of_partition
        # lines appended later become visible (tail semantics)
        with open(path, "a") as f:
            for r in rows[12:]:
                f.write(json.dumps(r) + "\n")
        b3 = fs.fetch(b2.next_offset, 100)
        assert len(b3) == 8
        assert fs.latest_offset() == 20

    def test_file_stream_table(self, tmp_path):
        import json

        path = str(tmp_path / "in.jsonl")
        rows = _rows(30)
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        cfg = TableConfig(
            name="events",
            stream=StreamConfig(stream_type="file", properties={"path": path}, max_rows_per_segment=100),
        )
        mgr = RealtimeTableDataManager(_schema(), cfg, str(tmp_path / "tbl"))
        mgr.consume_all()
        assert mgr.total_rows == 30
