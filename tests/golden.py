"""Golden-reference harness: run the same query on pinot_tpu and sqlite3 and
compare — the H2-checked query-correctness tier of the reference
(ClusterIntegrationTestUtils.setUpH2TableWithAvro, SURVEY.md section 4)."""
from __future__ import annotations

import math
import sqlite3
from typing import Dict, List, Optional, Sequence

import numpy as np


def sqlite_from_data(name: str, data: Dict[str, np.ndarray], nulls: Optional[Dict[str, np.ndarray]] = None):
    conn = sqlite3.connect(":memory:")
    cols = list(data)
    n = len(data[cols[0]])
    decls = []
    for c in cols:
        arr = np.asarray(data[c])
        if arr.dtype == object and any(isinstance(v, str) for v in arr if v is not None):
            decls.append(f'"{c}" TEXT')
        elif np.issubdtype(arr.dtype, np.floating) or (
            arr.dtype == object and any(isinstance(v, float) for v in arr if v is not None)
        ):
            decls.append(f'"{c}" REAL')
        else:
            decls.append(f'"{c}" INTEGER')
    conn.execute(f"CREATE TABLE {name} ({', '.join(decls)})")
    rows = []
    for i in range(n):
        row = []
        for c in cols:
            v = data[c][i]
            if nulls and c in nulls and nulls[c] is not None and nulls[c][i]:
                v = None
            elif isinstance(v, float) and math.isnan(v):
                v = None
            elif isinstance(v, np.generic):
                v = v.item()
            row.append(v)
        rows.append(tuple(row))
    conn.executemany(f"INSERT INTO {name} VALUES ({','.join('?' * len(cols))})", rows)
    conn.commit()
    return conn


def normalize_rows(rows: Sequence[Sequence], float_tol: float = 1e-6) -> List[tuple]:
    out = []
    for r in rows:
        nr = []
        for v in r:
            if isinstance(v, np.generic):
                v = v.item()
            if isinstance(v, float):
                if math.isnan(v):
                    v = None
                else:
                    v = round(v, 6)
                    if v == int(v) and abs(v) < 1e15:
                        v = float(v)  # keep float type but canonical
            nr.append(v)
        out.append(tuple(nr))
    return out


def _canon(v):
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return ("num", int(v))
    if isinstance(v, int):
        return ("num", v)
    if v is None:
        return ("null",)
    return (type(v).__name__, v)


def assert_same_rows(got: Sequence, expected: Sequence, ordered: bool = False):
    g = [tuple(_canon(v) for v in r) for r in normalize_rows(got)]
    e = [tuple(_canon(v) for v in r) for r in normalize_rows(expected)]
    if not ordered:
        g, e = sorted(g), sorted(e)
    assert g == e, f"rows differ:\n got      {g[:10]}\n expected {e[:10]}\n (lens {len(g)} vs {len(e)})"


def check_against_sqlite(engine, conn, sql_pinot: str, sql_lite: Optional[str] = None, ordered: bool = False):
    """Run on both engines and compare (sql_lite defaults to sql_pinot)."""
    res = engine.query(sql_pinot)
    expected = conn.execute(sql_lite or sql_pinot).fetchall()
    assert_same_rows(res.rows, expected, ordered=ordered)
    return res
