"""DST transition precision (ADVICE r5): _tz_table bisects to 1 ms, so an
instant 30 s before a shift lands in the PRE-shift offset and the boundary
instant itself in the POST-shift offset — the old 1-minute bisection could
misclassify up to a minute around each transition."""
import datetime as dt
from zoneinfo import ZoneInfo

import numpy as np

from pinot_tpu.query.scalar import _tz_offset_ms, _tz_table

NY = "America/New_York"
# 2024-03-10 07:00:00 UTC: America/New_York springs forward (EST -> EDT)
SPRING = 1_710_054_000_000
# 2024-11-03 06:00:00 UTC: falls back (EDT -> EST)
FALL = 1_730_613_600_000
H = 3_600_000


def test_table_records_exact_transition_instants():
    trans, offs = _tz_table(NY)
    assert SPRING in trans.tolist()
    assert FALL in trans.tolist()


def test_offset_flips_exactly_at_boundary():
    for boundary, before_off, after_off in (
        (SPRING, -5 * H, -4 * H),
        (FALL, -4 * H, -5 * H),
    ):
        ms = np.asarray(
            [boundary - 30_000, boundary - 1, boundary, boundary + 30_000], np.int64
        )
        got = np.asarray(_tz_offset_ms(ms, NY))
        assert got.tolist() == [before_off, before_off, after_off, after_off]


def test_thirty_seconds_before_shift_matches_zoneinfo():
    """Regression: 01:59:30 EST on the spring-forward morning must report
    the EST offset (the 60 s-precision table could flip it an hour early)."""
    z = ZoneInfo(NY)
    for instant in (SPRING - 30_000, FALL - 30_000):
        want = int(
            dt.datetime.fromtimestamp(instant / 1000, tz=z).utcoffset().total_seconds() * 1000
        )
        got = int(np.asarray(_tz_offset_ms(np.int64(instant), NY)))
        assert got == want
