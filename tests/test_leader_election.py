"""Coordinator HA (round 18): lease-based leader election, fenced journal
epochs, hot-standby failover.

The contract under test is Taurus-shaped: the durable journal is the
database, and availability comes from fencing WHO may write it.  A lease
file in meta_dir elects the leader and mints a monotonically increasing
epoch (the fencing token); every journal append carries its writer's epoch
and the journal refuses appends from a deposed one BEFORE any byte lands.
A hot standby tails the journal incrementally (the shared TailFollower),
promotes on lease expiry, and brokers ride a CoordinatorHandle across the
failover — data-plane queries keep serving off the last versioned routing
view the whole time.

The split-brain proof: pause the leader past expiry, promote the standby,
resume the old leader — its next durable write MUST fence, the on-disk
journal must show no interleaved epochs, and a third coordinator replaying
the directory must land on the new leader's exact state.
"""
import json
import os

import numpy as np
import pytest

from pinot_tpu.cluster.broker import Broker
from pinot_tpu.cluster.coordinator import Coordinator
from pinot_tpu.cluster.election import (
    CoordinatorHandle,
    FencedEpochError,
    JournalFollower,
    LeaseManager,
    NotLeaderError,
)
from pinot_tpu.cluster.faults import FaultPlan
from pinot_tpu.cluster.journal import JOURNAL_FILE
from pinot_tpu.cluster.server import ServerInstance
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import SegmentsConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.utils import crashpoints
from pinot_tpu.utils.crashpoints import InjectedCrash
from pinot_tpu.utils.metrics import METRICS

from golden import assert_same_rows


@pytest.fixture(autouse=True)
def _clean_kill_points():
    crashpoints.reset()
    yield
    crashpoints.reset()


class SimClock:
    """Injectable monotonic clock: the whole election runs in virtual time."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> "SimClock":
        self.t += s
        return self


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _data(n, seed, t0=1_700_000_000_000):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "v": rng.integers(0, 100, n),
        "ts": t0 + rng.integers(0, 86_400_000, n).astype(np.int64),
    }


def _fingerprint(coord):
    """Replayed-state identity: assignment + metadata + membership."""
    out = {"replication": coord.replication, "groups": dict(coord.replica_group)}
    for name, meta in sorted(coord.tables.items()):
        out[name] = {
            "ideal": {seg: sorted(srvs) for seg, srvs in meta.ideal.items()},
            "numDocs": {seg: m["numDocs"] for seg, m in meta.segment_meta.items()},
        }
    return out


TTL = 2.0


def _ha_cluster(tmp_path, clock, n_servers=3, replication=2, n_segments=3, rows=150):
    """Leader coordinator over a durable meta_dir + deep store, on the sim
    clock, with servers/table/segments loaded.  Standbys join per-test."""
    leader = Coordinator(
        replication=replication,
        meta_dir=str(tmp_path / "meta"),
        deep_store=str(tmp_path / "deep"),
        node_id="coord-a",
        lease_ttl_s=TTL,
        clock=clock,
    )
    servers = [
        ServerInstance(f"server{i}", data_dir=str(tmp_path / f"server{i}"))
        for i in range(n_servers)
    ]
    for s in servers:
        leader.register_server(s)
    leader.add_table(_schema(), TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    for i in range(n_segments):
        leader.add_segment(
            "t",
            build_segment(
                _schema(), _data(rows, seed=100 + i), f"seg{i}",
                output_dir=str(tmp_path / "build" / f"seg{i}"),
            ),
        )
    return leader, servers


def _standby(tmp_path, clock, node_id="coord-b"):
    return Coordinator(
        replication=2,
        meta_dir=str(tmp_path / "meta"),
        deep_store=str(tmp_path / "deep"),
        node_id=node_id,
        standby=True,
        lease_ttl_s=TTL,
        clock=clock,
    )


QUERIES = [
    "SELECT COUNT(*), SUM(v) FROM t",
    "SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city ORDER BY city",
]


# ---------------------------------------------------------------------------
# LeaseManager unit behavior
# ---------------------------------------------------------------------------
class TestLeaseManager:
    def test_acquire_expire_takeover_bumps_epoch(self, tmp_path):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        b = LeaseManager(str(tmp_path), "b", ttl_s=TTL, clock=clock)
        assert a.try_acquire() and a.epoch == 1 and a.is_leader
        # polite acquire refuses a live foreign lease
        assert not b.try_acquire()
        clock.advance(TTL + 0.1)
        assert b.try_acquire() and b.epoch == 2
        # the deposed holder discovers the loss on its next renew
        assert a.renew() is False and a.is_leader is False
        assert METRICS.counter("coordinator.leadershipLost").value == 1

    def test_renew_extends_the_deadline(self, tmp_path):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        b = LeaseManager(str(tmp_path), "b", ttl_s=TTL, clock=clock)
        assert a.try_acquire()
        clock.advance(TTL * 0.75)
        assert a.renew() is True
        clock.advance(TTL * 0.75)  # past the ORIGINAL deadline, not the renewed one
        assert not b.try_acquire()
        assert not b.expired()

    def test_corrupt_lease_quarantines_and_election_recovers(self, tmp_path):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        with open(a.lease_path, "w", encoding="utf-8") as f:
            f.write('{"holder": "a", "epo')  # torn write from a dead kernel
        assert a.read() is None
        assert os.path.exists(a.lease_path + ".corrupt-0")
        assert METRICS.counter("coordinator.leaseCorrupt").value == 1
        # an unreadable lease must not wedge the election forever
        assert a.try_acquire() and a.is_leader

    def test_force_acquire_fences_the_previous_holder(self, tmp_path):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        b = LeaseManager(str(tmp_path), "b", ttl_s=TTL, clock=clock)
        assert a.try_acquire() and a.epoch == 1
        assert b.try_acquire(force=True) and b.epoch == 2  # boot-time takeover
        with pytest.raises(FencedEpochError):
            a.validate_writer()
        assert a.is_leader is False  # the fence demotes in place

    def test_equal_epoch_foreign_holder_fences_the_race_loser(self, tmp_path):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        b = LeaseManager(str(tmp_path), "b", ttl_s=TTL, clock=clock)
        assert a.try_acquire()
        clock.advance(TTL + 0.1)
        # two racing acquisitions of the expired lease both bump to 2; b's
        # durable write lands last, so a is the loser whose write vanished
        assert b.try_acquire() and b.epoch == 2
        a.epoch, a.is_leader = 2, True
        with pytest.raises(FencedEpochError):
            a.validate_writer()
        assert b.validate_writer() == 2

    def test_release_hands_over_without_waiting_out_the_ttl(self, tmp_path):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        b = LeaseManager(str(tmp_path), "b", ttl_s=TTL, clock=clock)
        assert a.try_acquire()
        a.release()
        assert b.try_acquire() and b.epoch == 2  # polite, zero clock advance

    def test_clock_skew_rule_shifts_one_nodes_view(self, tmp_path):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        b = LeaseManager(str(tmp_path), "b", ttl_s=TTL, clock=clock)
        plan = FaultPlan().lease_clock_skew("b", (TTL + 1) * 1000.0)
        b.fault_plan = plan
        assert a.try_acquire()
        # b's clock runs TTL+1s ahead: it sees the fresh lease as expired
        assert b.expired() and not a.expired()
        assert b.try_acquire() and b.epoch == 2
        # the fence (not the clock) is what keeps the journal single-writer
        with pytest.raises(FencedEpochError):
            a.validate_writer()


class TestStaleLeaseTmpSweep:
    def test_boot_sweeps_stale_lease_tmp(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock, n_segments=1)
        del leader
        stale = tmp_path / "meta" / "lease.json.tmp"
        stale.write_text('{"holder": "ghost", "epoch": 99}')
        METRICS.reset()
        Coordinator(
            meta_dir=str(tmp_path / "meta"), node_id="coord-b",
            lease_ttl_s=TTL, clock=clock,
        )
        assert not stale.exists()
        assert METRICS.counter("coordinator.staleLeaseTmpSwept").value >= 1

    def test_crash_mid_acquire_leaves_only_a_sweepable_tmp(self, tmp_path):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        crashpoints.arm("election.acquire.after_write")
        with pytest.raises(InjectedCrash):
            a.try_acquire()
        # died between tmp write and rename: no committed lease exists
        assert not os.path.exists(a.lease_path)
        assert os.path.exists(a.lease_path + ".tmp")
        b = LeaseManager(str(tmp_path), "b", ttl_s=TTL, clock=clock)
        b.sweep_stale_tmp()
        assert not os.path.exists(a.lease_path + ".tmp")
        assert METRICS.counter("coordinator.staleLeaseTmpSwept").value == 1
        assert b.try_acquire() and b.epoch == 1  # nothing was committed

    def test_crash_after_replace_committed_the_lease(self, tmp_path):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        crashpoints.arm("election.acquire.after_replace")
        with pytest.raises(InjectedCrash):
            a.try_acquire()
        b = LeaseManager(str(tmp_path), "b", ttl_s=TTL, clock=clock)
        cur = b.read()
        assert cur is not None and cur.holder == "a" and cur.epoch == 1
        assert not b.try_acquire()  # committed and live: polite refusal
        clock.advance(TTL + 0.1)
        assert b.try_acquire() and b.epoch == 2


# ---------------------------------------------------------------------------
# standby tailing (shared TailFollower) + epoch-filtered replay
# ---------------------------------------------------------------------------
class TestStandbyTailing:
    def test_standby_applies_the_leaders_writes_incrementally(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock)
        standby = _standby(tmp_path, clock)
        assert standby.role == "standby"
        assert _fingerprint(standby) == _fingerprint(leader)
        leader.add_table(
            Schema("t2", [FieldSpec("x", DataType.LONG, role=FieldRole.METRIC)]),
            TableConfig(name="t2"),
        )
        assert standby.catch_up() >= 1
        assert "t2" in standby.tables
        assert _fingerprint(standby) == _fingerprint(leader)
        assert METRICS.counter("coordinator.standbyEntriesApplied").value >= 1

    def test_standby_resyncs_after_leader_compaction(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock)
        standby = _standby(tmp_path, clock)
        leader.add_table(
            Schema("t2", [FieldSpec("x", DataType.LONG, role=FieldRole.METRIC)]),
            TableConfig(name="t2"),
        )
        leader.checkpoint_metadata()  # snapshot + journal truncate under the tail
        leader.drop_table("t2")
        standby.catch_up()
        assert "t2" not in standby.tables
        assert _fingerprint(standby) == _fingerprint(leader)

    def test_follower_parks_a_torn_final_line(self, tmp_path):
        """The regression both TailFollower call sites share: a torn final
        line parks until the writer finishes it — never applied early,
        never skipped once complete."""
        meta = tmp_path / "meta"
        meta.mkdir()
        path = meta / JOURNAL_FILE
        with open(path, "w", encoding="utf-8") as f:
            f.write('{"seq": 1, "epoch": 1, "op": "noop"}\n')
            f.write('{"seq": 2, "epoch": 1, "op": "noop"}\n')
            f.write('{"seq": 3, "epoch": 1, "o')  # append died mid-line
        follower = JournalFollower(str(meta))
        _state, entries = follower.poll()
        assert [e["seq"] for e in entries] == [1, 2]
        with open(path, "a", encoding="utf-8") as f:
            f.write('p": "noop"}\n')  # the writer finished the line
        _state, entries = follower.poll()
        assert [e["seq"] for e in entries] == [3]

    def test_follower_drops_deposed_epoch_interleaving(self, tmp_path):
        meta = tmp_path / "meta"
        meta.mkdir()
        with open(meta / JOURNAL_FILE, "w", encoding="utf-8") as f:
            f.write('{"seq": 1, "epoch": 1, "op": "noop"}\n')
            f.write('{"seq": 2, "epoch": 2, "op": "noop"}\n')
            f.write('{"seq": 3, "epoch": 1, "op": "zombie"}\n')  # deposed writer
            f.write('{"seq": 4, "epoch": 2, "op": "noop"}\n')
        follower = JournalFollower(str(meta))
        _state, entries = follower.poll()
        assert [e["seq"] for e in entries] == [1, 2, 4]
        assert METRICS.counter("coordinator.fencedReplayDropped").value == 1


# ---------------------------------------------------------------------------
# the split-brain proof (satellite acceptance)
# ---------------------------------------------------------------------------
class TestSplitBrain:
    def test_zombie_leader_is_fenced_and_replay_matches_bit_for_bit(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock)
        plan = FaultPlan().attach_coordinator(leader)
        standby = _standby(tmp_path, clock)
        plan.attach_coordinator(standby)

        # freeze the leader (GC pause / VM stall) past lease expiry
        plan.pause_leader("coord-a")
        clock.advance(TTL + 0.1)
        assert standby.run_election_tick() == "leader"
        assert standby.election.epoch == 2
        standby.add_table(
            Schema("t2", [FieldSpec("x", DataType.LONG, role=FieldRole.METRIC)]),
            TableConfig(name="t2"),
        )

        # thaw the zombie: it still believes it leads — its next durable
        # write must fence BEFORE any byte reaches the journal
        plan.resume_leader("coord-a")
        assert leader.role == "leader"
        with pytest.raises(FencedEpochError):
            leader.drop_table("t")
        assert METRICS.counter("coordinator.fencedAppends").value == 1
        assert leader.role == "standby"  # fencing demotes in place

        # the on-disk journal shows no interleaved epochs
        with open(tmp_path / "meta" / JOURNAL_FILE, encoding="utf-8") as f:
            epochs = [json.loads(line)["epoch"] for line in f if line.strip()]
        assert epochs == sorted(epochs) and set(epochs) == {1, 2}

        # a third coordinator replaying the directory lands on the new
        # leader's EXACT state (the fenced drop never happened)
        third = Coordinator(
            meta_dir=str(tmp_path / "meta"), deep_store=str(tmp_path / "deep"),
            node_id="coord-c", lease_ttl_s=TTL, clock=clock,
        )
        assert "t" in third.tables and "t2" in third.tables
        assert _fingerprint(third) == _fingerprint(standby)

    def test_deposed_leader_rejoins_as_a_tailing_standby(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock)
        plan = FaultPlan().attach_coordinator(leader)
        standby = _standby(tmp_path, clock)
        plan.attach_coordinator(standby)
        plan.pause_leader("coord-a")
        clock.advance(TTL + 0.1)
        assert standby.run_election_tick() == "leader"
        plan.resume_leader("coord-a")
        # the thawed leader's own tick discovers the lost lease and demotes
        assert leader.run_election_tick() == "standby"
        standby.add_table(
            Schema("t2", [FieldSpec("x", DataType.LONG, role=FieldRole.METRIC)]),
            TableConfig(name="t2"),
        )
        leader.run_election_tick()  # now tails the NEW leader's journal
        assert "t2" in leader.tables
        assert _fingerprint(leader) == _fingerprint(standby)

    def test_paused_leader_refuses_control_plane_but_serves_reads(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock)
        plan = FaultPlan().attach_coordinator(leader)
        baseline = {sql: Broker(leader).query(sql).rows for sql in QUERIES}
        plan.pause_leader("coord-a")
        with pytest.raises(NotLeaderError):
            leader.mark_down("server0")
        broker = Broker(leader)
        for sql in QUERIES:
            res = broker.query(sql)
            assert_same_rows(res.rows, baseline[sql])
            assert res.stats.partial_result is False

    def test_renew_suppression_is_logged_by_the_plan(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock, n_segments=1)
        plan = FaultPlan().attach_coordinator(leader)
        plan.pause_leader("coord-a")
        # the frozen process's renewal simply never happens (returns True
        # unchanged — the lie the epoch fence exists to catch)
        assert leader.election.renew() is True
        assert METRICS.counter("coordinator.leaseRenewals").value == 0
        assert any(ev[2] == "renew_suppressed" for ev in plan.log)

    def test_journal_append_latency_rides_the_plan_sleep(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock, n_segments=1)
        plan = FaultPlan().attach_coordinator(leader)
        plan.journal_append_latency("coord-a", 50.0)
        slept = []
        plan.sleep = slept.append
        leader.add_table(
            Schema("t2", [FieldSpec("x", DataType.LONG, role=FieldRole.METRIC)]),
            TableConfig(name="t2"),
        )
        assert slept == [0.05]
        assert any(ev[2] == "journal_append_latency" for ev in plan.log)


# ---------------------------------------------------------------------------
# crash points inside the election protocol
# ---------------------------------------------------------------------------
class TestElectionCrashPoints:
    def test_crash_mid_promote_is_retryable(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock)
        plan = FaultPlan().attach_coordinator(leader)
        standby = _standby(tmp_path, clock)
        plan.attach_coordinator(standby)
        plan.pause_leader("coord-a")
        clock.advance(TTL + 0.1)
        plan.kill_at("election.promote.after_acquire")
        with pytest.raises(InjectedCrash):
            standby.run_election_tick()
        # died holding the lease but before adopting the journal: the next
        # tick re-acquires (own holder: no polite refusal) and finishes
        assert standby.role == "standby"
        assert standby.run_election_tick() == "leader"
        assert standby.journal is not None and standby.election.is_leader

    @pytest.mark.parametrize(
        "point", ["journal.append.before_fence", "journal.append.after_fence"]
    )
    def test_crash_around_the_fence_never_commits(self, tmp_path, point):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock)
        before = _fingerprint(leader)
        crashpoints.arm(point)
        with pytest.raises(InjectedCrash):
            leader.add_table(
                Schema("t2", [FieldSpec("x", DataType.LONG, role=FieldRole.METRIC)]),
                TableConfig(name="t2"),
            )
        replayed = Coordinator(
            meta_dir=str(tmp_path / "meta"), node_id="coord-r",
            lease_ttl_s=TTL, clock=clock,
        )
        assert "t2" not in replayed.tables
        assert _fingerprint(replayed) == before

    @pytest.mark.parametrize(
        "point,renewed",
        [
            # died between tmp write and rename: the OLD deadline stands
            ("election.renew.after_write", False),
            # died after the rename: the renewal committed durably
            ("election.renew.after_replace", True),
        ],
    )
    def test_crash_mid_renew_leaves_a_consistent_lease(self, tmp_path, point, renewed):
        clock = SimClock()
        a = LeaseManager(str(tmp_path), "a", ttl_s=TTL, clock=clock)
        assert a.try_acquire()
        clock.advance(0.5)
        crashpoints.arm(point)
        with pytest.raises(InjectedCrash):
            a.renew()
        cur = LeaseManager(str(tmp_path), "b", ttl_s=TTL, clock=clock).read()
        assert cur is not None and cur.holder == "a"
        assert cur.expires_at == pytest.approx((0.5 + TTL) if renewed else TTL)


# ---------------------------------------------------------------------------
# CoordinatorHandle: brokers ride the failover (chaos acceptance)
# ---------------------------------------------------------------------------
def _handled_cluster(tmp_path, clock):
    """Leader + hot standby behind a CoordinatorHandle whose park sleeps
    advance the sim clock (the park's auto-tick then promotes the standby
    once the lease expires) — the single-threaded failover-under-load rig."""
    leader, servers = _ha_cluster(tmp_path, clock)
    plan = FaultPlan().attach_coordinator(leader)
    standby = _standby(tmp_path, clock)
    plan.attach_coordinator(standby)
    handle = CoordinatorHandle(
        [leader, standby], sleep=lambda s: clock.advance(s), clock=clock
    )
    for s in servers:
        handle._servers[s.name] = s  # already registered pre-handle
    return leader, standby, plan, handle


class TestCoordinatorHandleFailover:
    def test_control_plane_write_parks_across_the_failover(self, tmp_path):
        clock = SimClock()
        leader, standby, plan, handle = _handled_cluster(tmp_path, clock)
        plan.pause_leader("coord-a")
        # no clock advance needed: the park's own backoff sleeps walk the
        # sim clock past lease expiry, the auto-tick promotes, the write lands
        handle.add_table(
            Schema("t2", [FieldSpec("x", DataType.LONG, role=FieldRole.METRIC)]),
            TableConfig(name="t2"),
        )
        assert standby.role == "leader" and "t2" in standby.tables
        assert METRICS.counter("coordinator.failoverParksServed").value >= 1
        assert handle.election_snapshot()["leader"] == "coord-b"

    def test_park_window_expiry_raises_structured_not_leader(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock, n_segments=1)
        plan = FaultPlan().attach_coordinator(leader)
        handle = CoordinatorHandle(
            [leader], park_ms=200, retries=1,
            sleep=lambda s: clock.advance(s), clock=clock,
        )
        plan.pause_leader("coord-a")  # no standby: nothing can take over
        with pytest.raises(NotLeaderError):
            handle.mark_down("server0")
        assert METRICS.counter("coordinator.failoverParkTimeouts").value >= 1

    @pytest.mark.parametrize(
        "point,committed",
        [
            # leader dies after the deep-store upload, before the journal
            # append: the assignment never committed — the retry on the new
            # leader is the FIRST commit (no double-add)
            ("coordinator.add_segment.after_upload", False),
            # leader dies after the journal append: committed — the new
            # leader replays it and the retry must be refused as a duplicate
            ("coordinator.add_segment.after_journal", True),
        ],
    )
    def test_leader_killed_mid_add_segment(self, tmp_path, point, committed):
        clock = SimClock()
        leader, standby, plan, handle = _handled_cluster(tmp_path, clock)
        broker = Broker(handle)
        baseline = {sql: broker.query(sql).rows for sql in QUERIES}
        seg = build_segment(
            _schema(), _data(80, seed=999), "seg_late",
            output_dir=str(tmp_path / "build" / "seg_late"),
        )
        plan.kill_at(point)
        with pytest.raises(InjectedCrash):
            handle.add_segment("t", seg)
        plan.pause_leader("coord-a")  # the crashed process never comes back
        handle.heartbeat("server0")  # any control-plane call drives the failover
        assert standby.role == "leader"
        # the journal is the truth: committed iff the append preceded death
        assert ("seg_late" in standby.tables["t"].ideal) == committed
        if not committed:
            handle.add_segment("t", seg)  # the retry is the FIRST commit
        res = broker.query("SELECT COUNT(*) FROM t")
        assert res.rows[0][0] == 3 * 150 + 80
        assert res.stats.partial_result is False
        for sql in QUERIES:  # pre-failover results stay exact, never doubled
            got = broker.query(sql)
            assert got.stats.partial_result is False
        del baseline

    def test_leader_killed_mid_rebalance_converges(self, tmp_path):
        clock = SimClock()
        leader, standby, plan, handle = _handled_cluster(tmp_path, clock)
        broker = Broker(handle)
        baseline = {sql: broker.query(sql).rows for sql in QUERIES}
        new_server = ServerInstance("server3", data_dir=str(tmp_path / "server3"))
        handle.register_server(new_server)
        plan.kill_at("rebalance.after_add")
        with pytest.raises(InjectedCrash):
            handle.rebalance("t")
        plan.pause_leader("coord-a")
        # queries during the blackout: exact or structured-partial, never garbage
        for sql in QUERIES:
            res = broker.query(sql)
            if res.stats.partial_result:
                assert res.stats.exceptions
            else:
                assert_same_rows(res.rows, baseline[sql])
        # the retried rebalance on the promoted standby converges
        handle.rebalance("t")
        assert standby.role == "leader"
        meta = standby.tables["t"]
        for seg, srvs in meta.ideal.items():
            assert len(srvs) == standby.replication
        for sql in QUERIES:
            res = broker.query(sql)
            assert res.stats.partial_result is False
            assert_same_rows(res.rows, baseline[sql])

    def test_data_plane_never_parks_during_blackout(self, tmp_path):
        clock = SimClock()
        leader, standby, plan, handle = _handled_cluster(tmp_path, clock)
        broker = Broker(handle)
        baseline = {sql: broker.query(sql).rows for sql in QUERIES}
        plan.pause_leader("coord-a")
        t0 = clock.t
        for sql in QUERIES:  # leaderless: served off the last routing view
            assert_same_rows(broker.query(sql).rows, baseline[sql])
        assert clock.t == t0  # zero park sleeps on the read path
        handle.heartbeat("server0")  # control plane parks + promotes
        assert standby.role == "leader"
        for sql in QUERIES:
            assert_same_rows(broker.query(sql).rows, baseline[sql])


class TestElectionSurfaces:
    def test_rest_debug_election_and_not_leader_503(self, tmp_path):
        import urllib.error
        import urllib.request

        from pinot_tpu.cluster.rest import QueryServer

        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock, n_segments=1)

        class _Engine:
            def election_snapshot(self):
                return leader.election_snapshot()

            def sql(self, _sql):
                raise NotLeaderError("coordinator coord-a is a standby")

        srv = QueryServer(_Engine()).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/election"
            ) as resp:
                snap = json.loads(resp.read().decode())
            assert snap["leader"] == "coord-a"
            assert snap["candidates"][0]["epoch"] == 1
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/query/sql",
                data=json.dumps({"sql": "SELECT 1"}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 503
            assert json.loads(ei.value.read().decode())["errorCode"] == "NOT_LEADER"
        finally:
            srv.stop()

    def test_cli_election_renders_snapshot(self, tmp_path, capsys):
        from pinot_tpu.cluster.rest import QueryServer
        from pinot_tpu.tools import cli

        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock, n_segments=1)

        class _Engine:
            def election_snapshot(self):
                return leader.election_snapshot()

        srv = QueryServer(_Engine()).start()
        try:
            rc = cli.main(["election", "--url", f"http://127.0.0.1:{srv.port}"])
            out = capsys.readouterr().out
            assert rc == 0
            assert "leader  : coord-a" in out
            assert "role=leader" in out and "epoch=1" in out
            rc = cli.main(
                ["election", "--url", f"http://127.0.0.1:{srv.port}", "--json"]
            )
            snap = json.loads(capsys.readouterr().out)
            assert rc == 0
            assert snap["leader"] == "coord-a"
        finally:
            srv.stop()

    def test_broker_election_snapshot_delegates(self, tmp_path):
        clock = SimClock()
        leader, _ = _ha_cluster(tmp_path, clock, n_segments=1)
        snap = Broker(leader).election_snapshot()
        assert snap["leader"] == "coord-a"
        assert snap["candidates"][0]["role"] == "leader"
