"""Minion task tests: merge/rollup, purge, realtime-to-offline."""
import numpy as np
import pytest

from pinot_tpu.cluster import Broker, Coordinator, ServerInstance
from pinot_tpu.cluster.minion import MinionTaskManager
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import SegmentsConfig, StreamConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _cluster():
    coord = Coordinator(replication=1)
    coord.register_server(ServerInstance("s0"))
    coord.add_table(_schema(), TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    return coord


def _data(n, seed, t0=1_700_000_000_000):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc"], n).astype(object),
        "v": rng.integers(0, 100, n),
        "ts": t0 + rng.integers(0, 1000, n).astype(np.int64),
    }


class TestMergeRollup:
    def test_merge_small_segments(self):
        coord = _cluster()
        cfg = coord.tables["t"].config
        total = 0
        for i in range(5):
            d = _data(200, seed=i)
            total += 200
            coord.add_segment("t", build_segment(_schema(), d, f"small{i}", table_config=cfg))
        broker = Broker(coord)
        before = broker.query("SELECT COUNT(*), SUM(v) FROM t").rows
        report = MinionTaskManager(coord).run("MergeRollupTask", "t", max_rows_per_segment=1000)
        assert report["merged"] == 1 and len(report["inputs"]) == 5
        assert len(coord.tables["t"].ideal) == 1  # five -> one
        after = broker.query("SELECT COUNT(*), SUM(v) FROM t").rows
        assert before == after

    def test_rollup_collapses_duplicates(self):
        coord = _cluster()
        cfg = coord.tables["t"].config
        # duplicate (city, ts) combos on purpose
        data = {
            "city": np.array(["sf", "sf", "nyc", "sf"], dtype=object),
            "v": np.array([1, 2, 3, 4]),
            "ts": np.array([100, 100, 100, 200], dtype=np.int64),
        }
        coord.add_segment("t", build_segment(_schema(), {k: v[:2] for k, v in data.items()}, "a", table_config=cfg))
        coord.add_segment("t", build_segment(_schema(), {k: v[2:] for k, v in data.items()}, "b", table_config=cfg))
        report = MinionTaskManager(coord).run("MergeRollupTask", "t", rollup=True)
        assert report["outputRows"] == 3  # (sf,100) collapsed
        broker = Broker(coord)
        rows = {(r[0], r[1]): r[2] for r in broker.query("SELECT city, ts, SUM(v) FROM t GROUP BY city, ts").rows}
        assert rows[("sf", 100)] == 3  # 1 + 2 rolled up


class TestPurge:
    def test_purge_rows(self):
        coord = _cluster()
        cfg = coord.tables["t"].config
        d = _data(500, seed=9)
        coord.add_segment("t", build_segment(_schema(), d, "seg", table_config=cfg))
        expected_keep = sum(1 for c in d["city"] if c != "nyc")
        report = MinionTaskManager(coord).run("PurgeTask", "t", purge_fn=lambda row: row["city"] == "nyc")
        assert report["purgedRows"] == 500 - expected_keep
        broker = Broker(coord)
        assert broker.query("SELECT COUNT(*) FROM t").rows[0][0] == expected_keep
        assert broker.query("SELECT COUNT(*) FROM t WHERE city = 'nyc'").rows[0][0] == 0


class TestRealtimeToOffline:
    def test_moves_sealed_segments(self, tmp_path):
        from pinot_tpu.realtime import InMemoryStream, RealtimeTableDataManager

        schema = _schema()
        cfg = TableConfig(
            name="t",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=50),
        )
        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(schema, cfg, str(tmp_path / "rt"), stream=stream)
        t0 = 1_700_000_000_000
        rows = [
            {"city": "sf", "v": i, "ts": t0 + i} for i in range(120)
        ]
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        assert len(mgr.sealed[0]) == 2

        coord = Coordinator(replication=1)
        coord.register_server(ServerInstance("s0"))
        minion = MinionTaskManager(coord)
        report = minion.run(
            "RealtimeToOfflineSegmentsTask",
            "t",
            realtime_manager=mgr,
            window_end_ms=t0 + 200,
        )
        assert len(report["moved"]) == 2
        assert not mgr.sealed[0]  # moved out of the realtime view
        broker = Broker(coord)
        res = broker.query(f"SELECT COUNT(*), SUM(v) FROM {report['offlineTable']}")
        assert res.rows[0][0] == 100  # two sealed 50-row segments
        # watermark advanced: re-running moves nothing
        report2 = minion.run(
            "RealtimeToOfflineSegmentsTask", "t", realtime_manager=mgr, window_end_ms=t0 + 400
        )
        assert report2["moved"] == []
