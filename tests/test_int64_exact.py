"""Exact wide-range int64 SUM through the chunked32 (TPU) policy.

Round-4 verdict weak #4: grouped SUM over int64 columns whose range exceeds
int32 silently degraded to f32 accumulation (~2^-24 relative error).  The
fix is a SIGNED-MAGNITUDE 8-bit limb decomposition (ops.segmented.
_int64_signed_limbs): bit-exact while sum(|v|) < 2^53, matching the
reference's double accumulate (SumAggregationFunction.java) and beating its
rounding for mixed-sign data.
"""
import numpy as np
import pytest

from pinot_tpu import ops
from pinot_tpu.ops import segmented
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import DataType, FieldSpec, Schema


def _exact_group_sum(codes, vals, mask, g):
    exp = np.zeros(g, dtype=object)
    np.add.at(exp, codes, np.where(mask, vals.astype(object), 0))
    return exp.astype(np.int64)


def test_sum_limb_plan64():
    assert ops.sum_limb_plan64(None, None) == 8
    assert ops.sum_limb_plan64(0, 255) == 1
    assert ops.sum_limb_plan64(-(1 << 31), (1 << 31) - 1) == 4
    assert ops.sum_limb_plan64(-(1 << 40), 1 << 40) == 6
    assert ops.sum_limb_plan64(-(1 << 63), (1 << 63) - 1) == 8


def test_group_sum_int64_chunked32(monkeypatch):
    monkeypatch.setattr(segmented, "accum_policy", lambda: "chunked32")
    rng = np.random.default_rng(11)
    n, g = 120_000, 257
    codes = rng.integers(0, g, n).astype(np.int32)
    # |v| < 2^35 (well past int32) keeps sum(|v|) < 2^53 over 120k rows
    vals = rng.integers(-(1 << 35), 1 << 35, n, dtype=np.int64)
    mask = rng.random(n) < 0.8
    got = np.asarray(ops.group_sum(vals, mask, codes, g)).astype(np.int64)
    np.testing.assert_array_equal(got, _exact_group_sum(codes, vals, mask, g))


def test_group_sum_int64_all_negative_ones(monkeypatch):
    """The two's-complement recombine catastrophe case: a column of -1s
    (every limb 255) must come back exactly -count, not 0."""
    monkeypatch.setattr(segmented, "accum_policy", lambda: "chunked32")
    n, g = 300_000, 8
    codes = (np.arange(n) % g).astype(np.int32)
    vals = np.full(n, -1, dtype=np.int64)
    mask = np.ones(n, bool)
    got = np.asarray(ops.group_sum(vals, mask, codes, g)).astype(np.int64)
    np.testing.assert_array_equal(got, np.full(g, -(n // g), np.int64))


def test_group_sum_int64_extremes(monkeypatch):
    """int64 min/max magnitudes survive the limb decomposition (single rows,
    so no addition rounding is involved — f64 holds +-2^63 exactly)."""
    monkeypatch.setattr(segmented, "accum_policy", lambda: "chunked32")
    vals = np.array([np.iinfo(np.int64).min, np.iinfo(np.int64).max, 0, 7], np.int64)
    codes = np.arange(4, dtype=np.int32)
    got = np.asarray(ops.group_sum(vals, np.ones(4, bool), codes, 4))
    assert got[0] == float(np.iinfo(np.int64).min)
    assert got[1] == float(np.iinfo(np.int64).max)
    assert got[2] == 0.0 and got[3] == 7.0


def test_masked_sum_int64_chunked32(monkeypatch):
    monkeypatch.setattr(segmented, "accum_policy", lambda: "chunked32")
    rng = np.random.default_rng(12)
    n = 200_000
    vals = rng.integers(-(1 << 34), 1 << 34, n, dtype=np.int64)
    mask = rng.random(n) < 0.6
    got = int(np.asarray(ops.masked_sum(vals, mask)))
    assert got == int(vals[mask].astype(object).sum())


def test_fused_int64_sum_entry(monkeypatch):
    monkeypatch.setattr(segmented, "accum_policy", lambda: "chunked32")
    rng = np.random.default_rng(13)
    n, g = 90_000, 100
    codes = rng.integers(0, g, n).astype(np.int32)
    vals = rng.integers(-(1 << 36), 1 << 36, n, dtype=np.int64)
    mask = rng.random(n) < 0.7
    import jax.numpy as jnp

    [table] = ops.fused_group_tables(
        [("int64_sum", jnp.asarray(vals), jnp.asarray(mask), 5)],
        jnp.asarray(codes), g,
    )
    np.testing.assert_array_equal(
        np.asarray(table).astype(np.int64), _exact_group_sum(codes, vals, mask, g)
    )


def test_fused_mixed_int64_and_f32_entries(monkeypatch):
    """int64 limbs stay exact when a float entry promotes the shared one-hot
    matrices to f32."""
    monkeypatch.setattr(segmented, "accum_policy", lambda: "chunked32")
    rng = np.random.default_rng(14)
    n, g = 70_000, 64
    codes = rng.integers(0, g, n).astype(np.int32)
    vals = rng.integers(-(1 << 35), 1 << 35, n, dtype=np.int64)
    floats = rng.normal(0, 10, n)
    mask = rng.random(n) < 0.9
    import jax.numpy as jnp

    tables = ops.fused_group_tables(
        [
            ("int64_sum", jnp.asarray(vals), jnp.asarray(mask), 8),
            ("f32_sum", jnp.asarray(floats), jnp.asarray(mask), None),
        ],
        jnp.asarray(codes), g,
    )
    np.testing.assert_array_equal(
        np.asarray(tables[0]).astype(np.int64), _exact_group_sum(codes, vals, mask, g)
    )


def test_engine_wide_int64_grouped_sum_exact(monkeypatch):
    """End-to-end: grouped SUM over a LONG column spanning > int32 range is
    bit-exact under the TPU policy and raises no degradation warning."""
    import warnings as _w

    monkeypatch.setattr(segmented, "accum_policy", lambda: "chunked32")
    rng = np.random.default_rng(15)
    n, g = 50_000, 40
    k = rng.integers(0, g, n).astype(np.int32)
    w = rng.integers(-(1 << 38), 1 << 38, n, dtype=np.int64)
    schema = Schema(
        "t", [FieldSpec("k", DataType.INT), FieldSpec("w", DataType.LONG)]
    )
    engine = QueryEngine()
    engine.register_table(schema, TableConfig("t"))
    engine.add_segment("t", build_segment(schema, {"k": k, "w": w}, "s0"))
    with _w.catch_warnings():
        _w.simplefilter("error")
        res = engine.query(f"SELECT k, SUM(w) FROM t GROUP BY k ORDER BY k LIMIT {g}")
    exp = _exact_group_sum(k, w, np.ones(n, bool), g)
    got = {int(r[0]): int(r[1]) for r in res.rows}
    assert got == {i: int(exp[i]) for i in range(g)}
