"""Upsert + dedup tests: latest-row visibility across mutable + sealed
segments, restart bootstrap, dedup dropping.

Golden model: sqlite window query picking the max-comparison row per PK —
the visibility contract of ConcurrentMapPartitionUpsertMetadataManager.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.realtime import InMemoryStream, RealtimeTableDataManager
from pinot_tpu.spi.config import DedupConfig, StreamConfig, TableConfig, UpsertConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data


def _schema():
    return Schema(
        name="orders",
        fields=[
            FieldSpec("order_id", DataType.STRING),
            FieldSpec("status", DataType.STRING),
            FieldSpec("amount", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("updated_at", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
        primary_key_columns=["order_id"],
    )


def _config(max_rows=30, sorted_column=None, dedup=False):
    from pinot_tpu.spi.config import IndexingConfig, SegmentsConfig

    cfg = TableConfig(
        name="orders",
        indexing=IndexingConfig(sorted_column=sorted_column),
        segments=SegmentsConfig(time_column="updated_at"),
        stream=StreamConfig(stream_type="memory", max_rows_per_segment=max_rows),
    )
    if dedup:
        cfg.dedup = DedupConfig(enabled=True)
    else:
        cfg.upsert = UpsertConfig(mode="FULL", comparison_column="updated_at")
    return cfg


def _updates(n_keys=20, n_updates=80, seed=3):
    """Rows repeatedly updating a small key space."""
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n_updates):
        k = int(rng.integers(0, n_keys))
        rows.append(
            {
                "order_id": f"ord{k}",
                "status": ["open", "paid", "shipped"][int(rng.integers(0, 3))],
                "amount": float(np.round(rng.uniform(1, 100), 2)),
                "updated_at": 1_700_000_000_000 + i,  # strictly increasing
            }
        )
    return rows


def _latest_per_key(rows):
    latest = {}
    for r in rows:
        cur = latest.get(r["order_id"])
        if cur is None or r["updated_at"] >= cur["updated_at"]:
            latest[r["order_id"]] = r
    return list(latest.values())


def _engine_for(mgr, cfg):
    eng = QueryEngine()
    eng.register_table(_schema(), cfg)
    eng.attach_realtime("orders", mgr)
    return eng


def _golden(rows):
    data = {k: np.array([r[k] for r in rows], dtype=object) for k in rows[0]}
    return sqlite_from_data("orders", data)


QUERIES = [
    "SELECT COUNT(*), SUM(amount) FROM orders",
    "SELECT status, COUNT(*), SUM(amount) FROM orders GROUP BY status",
    "SELECT COUNT(*) FROM orders WHERE amount > 50",
]


class TestUpsert:
    def test_only_latest_rows_visible(self, tmp_path):
        cfg = _config()
        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(_schema(), cfg, str(tmp_path / "t"), stream=stream)
        eng = _engine_for(mgr, cfg)
        rows = _updates()
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        assert len(mgr.sealed[0]) == 2  # 80 rows, seal at 30 -> 2 sealed + 20 consuming
        conn = _golden(_latest_per_key(rows))
        for sql in QUERIES:
            assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_upsert_across_sealed_and_consuming(self, tmp_path):
        """A key updated in the consuming segment invalidates its sealed row."""
        cfg = _config(max_rows=5)
        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(_schema(), cfg, str(tmp_path / "t"), stream=stream)
        eng = _engine_for(mgr, cfg)
        first = [
            {"order_id": f"k{i}", "status": "open", "amount": 10.0, "updated_at": 1000 + i} for i in range(5)
        ]
        stream.publish_many(first, partition=0)
        mgr.consume_all()
        assert len(mgr.sealed[0]) == 1
        # update k2 in the (new) consuming segment
        stream.publish({"order_id": "k2", "status": "paid", "amount": 99.0, "updated_at": 2000}, partition=0)
        mgr.consume_all()
        res = eng.query("SELECT status, COUNT(*), SUM(amount) FROM orders GROUP BY status")
        rows = {r[0]: (r[1], r[2]) for r in res.rows}
        assert rows["open"] == (4, 40.0)
        assert rows["paid"] == (1, 99.0)

    def test_upsert_with_sorted_segment(self, tmp_path):
        """Seal-time segment sort must remap validDocIds through the
        permutation (builder sort_order)."""
        cfg = _config(max_rows=10, sorted_column="status")
        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(_schema(), cfg, str(tmp_path / "t"), stream=stream)
        eng = _engine_for(mgr, cfg)
        rows = _updates(n_keys=6, n_updates=25, seed=9)
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        assert len(mgr.sealed[0]) == 2
        conn = _golden(_latest_per_key(rows))
        for sql in QUERIES:
            assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_restart_bootstrap(self, tmp_path):
        cfg = _config(max_rows=20)
        stream = InMemoryStream(1)
        data_dir = str(tmp_path / "t")
        mgr = RealtimeTableDataManager(_schema(), cfg, data_dir, stream=stream)
        rows = _updates(n_keys=10, n_updates=60, seed=5)
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        del mgr
        # restart: pk map + masks rebuilt from sealed segments, tail replayed
        mgr2 = RealtimeTableDataManager(_schema(), cfg, data_dir, stream=stream)
        mgr2.consume_all()
        eng = _engine_for(mgr2, cfg)
        conn = _golden(_latest_per_key(rows))
        for sql in QUERIES:
            assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())


class TestDedup:
    def test_duplicates_dropped(self, tmp_path):
        cfg = _config(max_rows=50, dedup=True)
        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(_schema(), cfg, str(tmp_path / "t"), stream=stream)
        eng = _engine_for(mgr, cfg)
        rows = _updates(n_keys=15, n_updates=70, seed=11)
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        # first row per key wins
        firsts = {}
        for r in rows:
            firsts.setdefault(r["order_id"], r)
        assert mgr.total_rows == len(firsts)
        conn = _golden(list(firsts.values()))
        for sql in QUERIES:
            assert_same_rows(eng.query(sql).rows, conn.execute(sql).fetchall())

    def test_dedup_survives_restart(self, tmp_path):
        cfg = _config(max_rows=10, dedup=True)
        stream = InMemoryStream(1)
        data_dir = str(tmp_path / "t")
        mgr = RealtimeTableDataManager(_schema(), cfg, data_dir, stream=stream)
        rows = [{"order_id": f"k{i % 8}", "status": "open", "amount": 1.0, "updated_at": i} for i in range(30)]
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        assert mgr.total_rows == 8
        del mgr
        mgr2 = RealtimeTableDataManager(_schema(), cfg, data_dir, stream=stream)
        mgr2.consume_all()
        assert mgr2.total_rows == 8


class TestPartialUpsert:
    def test_partial_strategies(self, tmp_path):
        """PARTIAL mode: INCREMENT accumulates, IGNORE keeps first,
        OVERWRITE replaces (None keeps old) — PartialUpsertHandler analog."""
        from pinot_tpu.spi.config import SegmentsConfig, StreamConfig

        schema = Schema(
            name="acct",
            fields=[
                FieldSpec("k", DataType.STRING),
                FieldSpec("plan", DataType.STRING),
                FieldSpec("clicks", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
            primary_key_columns=["k"],
        )
        cfg = TableConfig(
            name="acct",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=4),
            upsert=UpsertConfig(
                mode="PARTIAL",
                comparison_column="ts",
                partial_upsert_strategies={"clicks": "INCREMENT", "plan": "IGNORE"},
            ),
        )
        from pinot_tpu.realtime import InMemoryStream, RealtimeTableDataManager

        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(schema, cfg, str(tmp_path / "acct"), stream=stream)
        eng = QueryEngine()
        eng.register_table(schema, cfg)
        eng.attach_realtime("acct", mgr)
        events = [
            {"k": "a", "plan": "free", "clicks": 1, "ts": 1},
            {"k": "b", "plan": "pro", "clicks": 10, "ts": 2},
            {"k": "a", "plan": "ent", "clicks": 2, "ts": 3},   # plan IGNOREd, clicks += 2
            {"k": "a", "plan": None, "clicks": 4, "ts": 4},    # clicks += 4
            {"k": "b", "plan": "ent", "clicks": 5, "ts": 5},   # clicks += 5
        ]
        stream.publish_many(events, partition=0)
        mgr.consume_all()
        res = eng.query("SELECT COUNT(*), SUM(clicks) FROM acct")
        assert res.rows[0][0] == 2           # one live row per key
        assert res.rows[0][1] == 7 + 15      # a: 1+2+4, b: 10+5
        plans = eng.query("SELECT plan, COUNT(*) FROM acct GROUP BY plan ORDER BY plan")
        assert {r[0] for r in plans.rows} == {"free", "pro"}  # IGNORE kept firsts

    def test_partial_merge_across_seal(self, tmp_path):
        """The merge reads the winning row even after it sealed."""
        from pinot_tpu.spi.config import SegmentsConfig, StreamConfig

        schema = Schema(
            name="acct",
            fields=[
                FieldSpec("k", DataType.STRING),
                FieldSpec("clicks", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
            primary_key_columns=["k"],
        )
        cfg = TableConfig(
            name="acct",
            segments=SegmentsConfig(time_column="ts"),
            stream=StreamConfig(stream_type="memory", max_rows_per_segment=2),
            upsert=UpsertConfig(
                mode="PARTIAL", comparison_column="ts",
                partial_upsert_strategies={"clicks": "INCREMENT"},
            ),
        )
        from pinot_tpu.realtime import InMemoryStream, RealtimeTableDataManager

        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(schema, cfg, str(tmp_path / "acct"), stream=stream)
        eng = QueryEngine()
        eng.register_table(schema, cfg)
        eng.attach_realtime("acct", mgr)
        stream.publish_many(
            [{"k": "a", "clicks": 3, "ts": 1}, {"k": "b", "clicks": 1, "ts": 2}], partition=0
        )
        mgr.consume_all()
        assert len(mgr.sealed[0]) == 1  # both rows sealed
        stream.publish({"k": "a", "clicks": 10, "ts": 3}, partition=0)
        mgr.consume_all()
        res = eng.query("SELECT SUM(clicks) FROM acct")
        assert res.rows[0][1 - 1] == 13 + 1  # a merged 3+10 across the seal, b intact


class TestUpsertCompaction:
    def test_from_segments_drops_invalidated_rows(self, tmp_path):
        """Stacking upsert segments compacts validDocIds away (the
        UpsertCompaction-at-load analog) — the distributed engine then
        serves only the latest rows with no masks."""
        from pinot_tpu.parallel.engine import DistributedEngine
        from pinot_tpu.parallel.stacked import StackedTable

        cfg = _config(max_rows=20)
        stream = InMemoryStream(1)
        mgr = RealtimeTableDataManager(_schema(), cfg, str(tmp_path / "t"), stream=stream)
        rows = _updates(n_keys=8, n_updates=60, seed=21)
        stream.publish_many(rows, partition=0)
        mgr.consume_all()
        assert len(mgr.sealed[0]) == 3
        st = StackedTable.from_segments(mgr.sealed[0], num_shards=8)
        eng = DistributedEngine()
        eng.register_table("orders", st)
        latest = _latest_per_key(rows)  # all 60 rows sealed (3 x 20)
        conn = _golden(latest)
        res = eng.query("SELECT COUNT(*), SUM(amount) FROM orders")
        exp = conn.execute("SELECT COUNT(*), SUM(amount) FROM orders").fetchall()
        from golden import assert_same_rows

        assert_same_rows(res.rows, exp)
