"""Safety + observability tests: timeouts, admission control, metrics,
trace spans, EXPLAIN PLAN.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.query.safety import AdmissionError, Deadline, QueryTimeoutError
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.utils.metrics import METRICS, Trace


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )


def _engine(budget=8 << 30, n=5000, segments=3):
    rng = np.random.default_rng(61)
    eng = QueryEngine(memory_budget_bytes=budget)
    cfg = TableConfig(name="t", indexing=IndexingConfig(inverted_index_columns=["city"]))
    eng.register_table(_schema(), cfg)
    for i in range(segments):
        data = {"city": rng.choice(["sf", "nyc"], n).astype(object), "v": rng.integers(0, 100, n)}
        eng.add_segment("t", build_segment(_schema(), data, f"s{i}", table_config=cfg))
    return eng


class TestTimeout:
    def test_expired_deadline_raises(self):
        eng = _engine()
        with pytest.raises(QueryTimeoutError, match="timeoutMs"):
            eng.query("SET timeoutMs = 0.000001; SELECT city, COUNT(*) FROM t GROUP BY city")

    def test_generous_deadline_passes(self):
        eng = _engine()
        res = eng.query("SET timeoutMs = 60000; SELECT COUNT(*) FROM t")
        assert res.rows[0][0] == 15000

    def test_deadline_helper(self):
        d = Deadline(None)
        d.check()  # no timeout: never raises
        d2 = Deadline(0.0000001)
        import time

        time.sleep(0.001)
        with pytest.raises(QueryTimeoutError):
            d2.check()


class TestAdmission:
    def test_oversized_query_rejected_upfront(self):
        eng = _engine(budget=1000)  # 1 KB budget: nothing real fits
        with pytest.raises(AdmissionError, match="device memory"):
            eng.query("SELECT SUM(v) FROM t")

    def test_budget_released_after_queries(self):
        eng = _engine()
        for _ in range(3):
            eng.query("SELECT COUNT(*) FROM t")
        assert eng.accountant.in_use == 0

    def test_release_on_failure(self):
        eng = _engine()
        with pytest.raises(Exception):
            eng.query("SELECT nonexistent_column FROM t")
        assert eng.accountant.in_use == 0


class TestMetricsAndTrace:
    def test_metrics_accumulate(self):
        METRICS.reset()
        eng = _engine()
        eng.query("SELECT COUNT(*) FROM t")
        eng.query("SELECT city, SUM(v) FROM t GROUP BY city")
        snap = METRICS.snapshot()
        assert snap["counters"]["queries"] == 2
        assert snap["counters"]["docsScanned"] == 30000
        assert snap["histograms"]["queryLatency"]["count"] == 2
        assert snap["histograms"]["queryLatency"]["maxMs"] > 0
        assert snap["histograms"]["queryLatency"]["p99Ms"] > 0

    def test_trace_spans(self):
        eng = _engine()
        res = eng.query("SET trace = true; SELECT city, COUNT(*) FROM t GROUP BY city")
        tr = res.stats.trace
        assert tr is not None and tr["name"] == "query"
        names = [c["name"] for c in tr["children"]]
        assert "reduce" in names
        assert sum(1 for n in names if n.startswith("launch:")) == 3
        assert sum(1 for n in names if n == "collect") == 3
        assert all(c["ms"] >= 0 for c in tr["children"])

    def test_trace_off_by_default(self):
        eng = _engine()
        res = eng.query("SELECT COUNT(*) FROM t")
        assert res.stats.trace is None


class TestExplain:
    def test_explain_groupby_with_index(self):
        eng = _engine()
        res = eng.query("EXPLAIN PLAN FOR SELECT city, SUM(v) FROM t WHERE city = 'sf' GROUP BY city")
        assert res.columns == ["Operator", "Operator_Id", "Parent_Id"]
        ops = [r[0] for r in res.rows]
        assert any(o.startswith("BROKER_REDUCE") for o in ops)
        assert any(o.startswith("GROUP_BY") for o in ops)
        assert any("FILTER" in o for o in ops)
        # parent ids form a chain rooted at 0
        ids = {r[1] for r in res.rows}
        assert all(r[2] in ids | {0} for r in res.rows)

    def test_explain_runs_nothing(self):
        METRICS.reset()
        eng = _engine()
        eng.query("EXPLAIN PLAN FOR SELECT COUNT(*) FROM t")
        assert METRICS.snapshot()["counters"].get("docsScanned", 0) == 0


class TestEnvConfigLayering:
    def test_env_option_applies_and_query_overrides(self, monkeypatch):
        from pinot_tpu.spi.env import env_options

        monkeypatch.setenv("PINOT_TPU_OPT_numGroupsLimit", "7")
        monkeypatch.setenv("PINOT_TPU_OPT_enableNullHandling", "false")
        opts = env_options()
        assert opts["numGroupsLimit"] == 7 and opts["enableNullHandling"] is False
        eng = _engine(n=500, segments=1)
        # env default caps the group count...
        res = eng.query("SELECT v, COUNT(*) FROM t GROUP BY v LIMIT 1000")
        assert len(res.rows) <= 7
        # ...but an explicit per-query SET wins over the env layer
        res2 = eng.query("SET numGroupsLimit = 1000; SELECT v, COUNT(*) FROM t GROUP BY v LIMIT 1000")
        assert len(res2.rows) > 7


class TestWorkloadScheduler:
    """BinaryWorkloadScheduler analog: secondary workload isolation."""

    def test_primary_never_queued(self):
        from pinot_tpu.query.ir import QueryContext
        from pinot_tpu.query.safety import WorkloadScheduler

        ws = WorkloadScheduler(secondary_slots=1)
        ctx = QueryContext(table="t", select_list=[])
        rels = [ws.acquire(ctx) for _ in range(10)]  # primary: unbounded
        for r in rels:
            r()

    def test_secondary_bounded(self):
        from pinot_tpu.query.ir import QueryContext
        from pinot_tpu.query.safety import AdmissionError, Deadline, WorkloadScheduler

        ws = WorkloadScheduler(secondary_slots=2)
        ctx = QueryContext(table="t", select_list=[], options={"isSecondaryWorkload": "true"})
        d = Deadline(50.0)  # 50ms: don't block the test
        r1 = ws.acquire(ctx, d)
        r2 = ws.acquire(ctx, d)
        with pytest.raises(AdmissionError):
            ws.acquire(ctx, Deadline(50.0))
        r1()
        r3 = ws.acquire(ctx, Deadline(50.0))  # freed slot admits again
        r3(); r2()

    def test_engine_option_roundtrip(self):
        import numpy as np

        from pinot_tpu.query.engine import QueryEngine
        from pinot_tpu.segment.builder import build_segment
        from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

        schema = Schema("w", [FieldSpec("v", DataType.INT, role=FieldRole.METRIC)])
        eng = QueryEngine(secondary_slots=1)
        eng.register_table(schema)
        eng.add_segment("w", build_segment(schema, {"v": np.arange(100, dtype=np.int32)}, "s0"))
        r = eng.query("SET isSecondaryWorkload = true; SELECT COUNT(*) FROM w")
        assert int(r.rows[0][0]) == 100
