"""Sketch aggregations on the SPARSE group-by path (round-5 VERDICT #3).

The reference handles high-cardinality group-by with ANY aggregation
(pinot-core/.../query/aggregation/groupby/DefaultGroupByExecutor.java:51 +
object result holders).  Here the sparse sort-scatter kernel hands each
vector-field function its own partial_grouped over slot ids
(planner.sparse_grouped_tables), so `SET maxDenseGroups=<small>` forcing
the sparse path must produce results identical to the dense path / sqlite.
"""
import numpy as np
import pytest

from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.sql.parser import parse_query
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data

N = 40_000
SPARSE = "SET maxDenseGroups = 2; "


def _schema():
    return Schema(
        "sk",
        [
            FieldSpec("g", DataType.INT),
            FieldSpec("v", DataType.INT),
            FieldSpec("w", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("t", DataType.LONG),
            FieldSpec("s", DataType.STRING),
        ],
    )


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(23)
    return {
        "g": rng.integers(0, 40, N).astype(np.int32),
        "v": rng.integers(0, 900, N).astype(np.int32),
        "w": np.round(rng.random(N) * 1000, 3),
        "t": rng.integers(0, 10_000, N),
        "s": np.array([f"u{int(x)}" for x in rng.integers(0, 300, N)], dtype=object),
    }


@pytest.fixture(scope="module")
def sse(data):
    eng = QueryEngine()
    eng.register_table(_schema())
    eng.add_segment("sk", build_segment(_schema(), data, "s0"))
    return eng


@pytest.fixture(scope="module")
def conn(data):
    return sqlite_from_data("sk", data)


def _forced_sparse(engine, sql):
    """Run with maxDenseGroups=2 and assert the sparse plan actually ran."""
    ctx = parse_query(SPARSE + sql)
    assert ctx.max_dense_groups == 2
    return engine.execute(ctx)


class TestSketchOnSparsePath:
    def test_plan_kind_is_sparse(self, sse):
        from pinot_tpu.query import planner

        ctx = parse_query(SPARSE + "SELECT g, DISTINCTCOUNTHLL(s) FROM sk GROUP BY g")
        plan = planner.plan_segment(ctx, sse.table("sk").segments[0])
        assert plan.kind == "groupby_sparse"

    def test_exact_distinctcount_vs_sqlite(self, sse, conn):
        sql = "SELECT g, DISTINCTCOUNT(v) FROM sk GROUP BY g ORDER BY g LIMIT 100"
        got = _forced_sparse(sse, sql)
        exp = conn.execute(
            "SELECT g, COUNT(DISTINCT v) FROM sk GROUP BY g ORDER BY g LIMIT 100"
        ).fetchall()
        assert_same_rows(got.rows, exp, ordered=True)

    def test_distinctcount_string_vs_sqlite(self, sse, conn):
        sql = "SELECT g, DISTINCTCOUNT(s) FROM sk GROUP BY g ORDER BY g LIMIT 100"
        got = _forced_sparse(sse, sql)
        exp = conn.execute(
            "SELECT g, COUNT(DISTINCT s) FROM sk GROUP BY g ORDER BY g LIMIT 100"
        ).fetchall()
        assert_same_rows(got.rows, exp, ordered=True)

    @pytest.mark.parametrize(
        "agg",
        [
            "DISTINCTCOUNTHLL(s)",
            "PERCENTILE(w, 95)",
            "PERCENTILEKLL(w, 50)",
            "MODE(v)",
            "DISTINCTCOUNTTHETA(v)",
            "LASTWITHTIME(v, t, 'LONG')",
            "FIRSTWITHTIME(v, t, 'LONG')",
        ],
    )
    def test_sparse_matches_dense(self, sse, agg):
        """Same registers/histograms/sketches must come out of both paths."""
        sql = f"SELECT g, {agg} FROM sk GROUP BY g ORDER BY g LIMIT 100"
        dense = sse.query(sql)
        sparse = _forced_sparse(sse, sql)
        assert_same_rows(sparse.rows, dense.rows, ordered=True)

    def test_mixed_scalar_and_sketch(self, sse, conn):
        sql = (
            "SELECT g, COUNT(*), SUM(v), DISTINCTCOUNT(v) FROM sk "
            "GROUP BY g ORDER BY g LIMIT 100"
        )
        got = _forced_sparse(sse, sql)
        exp = conn.execute(
            "SELECT g, COUNT(*), SUM(v), COUNT(DISTINCT v) FROM sk "
            "GROUP BY g ORDER BY g LIMIT 100"
        ).fetchall()
        assert_same_rows(got.rows, exp, ordered=True)

    def test_filtered_sketch_sparse(self, sse, conn):
        sql = (
            "SELECT g, DISTINCTCOUNT(v) FROM sk WHERE w > 500 "
            "GROUP BY g ORDER BY g LIMIT 100"
        )
        got = _forced_sparse(sse, sql)
        exp = conn.execute(
            "SELECT g, COUNT(DISTINCT v) FROM sk WHERE w > 500 "
            "GROUP BY g ORDER BY g LIMIT 100"
        ).fetchall()
        assert_same_rows(got.rows, exp, ordered=True)

    def test_high_cardinality_composite_key_hll(self, sse):
        """The actual bread-and-butter shape: a genuinely high-card composite
        key (40 x 900 = 36k groups) with DISTINCTCOUNTHLL — sparse by
        default config, trimmed to numGroupsLimit."""
        ctx = parse_query(
            "SET maxDenseGroups = 16; SET numGroupsLimit = 1000; "
            "SELECT g, v, DISTINCTCOUNTHLL(s, 8) FROM sk "
            "GROUP BY g, v ORDER BY g, v LIMIT 50"
        )
        res = sse.execute(ctx)
        assert len(res.rows) == 50
        # log2m=8 keeps the DENSE comparison under the cell budget too (the
        # sparse path at numGroupsLimit=1000 slots fits even log2m=12)
        dense = sse.query(
            "SELECT g, v, DISTINCTCOUNTHLL(s, 8) FROM sk GROUP BY g, v ORDER BY g, v LIMIT 50"
        )
        assert_same_rows(res.rows, dense.rows, ordered=True)


class TestDistributedSketchSparse:
    @pytest.fixture(scope="class")
    def dist(self, data):
        st = StackedTable.build(_schema(), data, 8)
        eng = DistributedEngine()
        eng.register_table("sk", st)
        return eng

    def test_distributed_hll_sparse_matches_dense(self, dist):
        sql = "SELECT g, DISTINCTCOUNTHLL(s) FROM sk GROUP BY g ORDER BY g LIMIT 100"
        dense = dist.query(sql)
        sparse = dist.query(SPARSE + sql)
        assert_same_rows(sparse.rows, dense.rows, ordered=True)

    def test_distributed_exact_distinctcount_sparse(self, dist, conn):
        """Cross-device slot merge must UNION presence bitmaps, not add."""
        sql = "SELECT g, DISTINCTCOUNT(v) FROM sk GROUP BY g ORDER BY g LIMIT 100"
        got = dist.query(SPARSE + sql)
        exp = conn.execute(
            "SELECT g, COUNT(DISTINCT v) FROM sk GROUP BY g ORDER BY g LIMIT 100"
        ).fetchall()
        assert_same_rows(got.rows, exp, ordered=True)

    def test_distributed_lastwithtime_sparse(self, dist, sse):
        """Pairwise-merge partials fold across device tables host-side
        (time-ties resolve to max v on both paths, so results are exact)."""
        sql = "SELECT g, LASTWITHTIME(v, t, 'LONG') FROM sk GROUP BY g ORDER BY g LIMIT 100"
        sparse = dist.query(SPARSE + sql)
        dense_single = sse.query(sql)
        assert_same_rows(sparse.rows, dense_single.rows, ordered=True)
