"""Tier-1 gate for the deterministic-schedule concurrency model checker
(analysis/scheduler.py + analysis/model_check.py).

Three properties, each a hard gate:

  * CLEAN: every registered protocol model explores its seeded schedule
    budget without a failure, inside a wall-clock budget (the checker is
    a pre-merge tool, not an overnight one).
  * MUTATION COVERAGE: every broken twin (a protocol subclass with one
    surgically reintroduced bug) is CAUGHT within the same budget — the
    checker's invariants actually discriminate, they aren't tautologies.
  * REPLAY DETERMINISM: a captured failing trace re-runs bit-identically
    — same failure kind, detail, step index, and schedule — across
    repeated replays and through a JSON round-trip.  "Capture once,
    replay forever" is the debugging contract.

The suite runs under the real tier-1 flags (-p no:randomly among them);
determinism here is by construction (seeded RNG + forced schedules +
fake clock), not by test-ordering luck.
"""
import json
import time

import pytest

from pinot_tpu.analysis import model_check
from pinot_tpu.analysis.models import PROTOCOLS
from pinot_tpu.utils import threads

ALL_MUTATIONS = [
    (name, mut)
    for name, cls in sorted(PROTOCOLS.items())
    for mut in getattr(cls, "MUTATIONS", ())
]


def test_clean_models_pass_within_budget():
    t0 = time.monotonic()
    report = model_check.check_all(seed=0, max_schedules=25, mutations=True)
    elapsed = time.monotonic() - t0
    assert report["ok"] is True, json.dumps(report, indent=2)
    assert set(report["protocols"]) == set(PROTOCOLS)
    for name, entry in report["protocols"].items():
        assert entry["failure"] is None, f"{name}: {entry['failure']}"
        assert entry["invariants"], f"{name} registered no invariants"
    assert elapsed < 10.0, f"mc gate took {elapsed:.1f}s (budget 10s)"


@pytest.mark.parametrize("protocol,mutation", ALL_MUTATIONS)
def test_every_mutation_is_caught(protocol, mutation):
    res = model_check.explore(
        PROTOCOLS[protocol], max_schedules=25, seed=0, mutation=mutation
    )
    assert res["failure"] is not None, (
        f"broken twin {protocol}[{mutation}] survived the schedule budget — "
        "the invariants are not discriminating"
    )


@pytest.mark.parametrize("protocol,mutation", ALL_MUTATIONS)
def test_failing_trace_replays_bit_identically(protocol, mutation):
    trace = model_check.explore(
        PROTOCOLS[protocol], max_schedules=25, seed=0, mutation=mutation
    )
    want = trace["failure"]
    assert want is not None
    for _ in range(2):
        got = model_check.replay(trace)
        assert got is not None, "forced replay lost the failure"
        for key in ("kind", "detail", "step", "schedule"):
            assert got[key] == want[key], (
                f"replay diverged on {key}: {got[key]!r} != {want[key]!r}"
            )


def test_trace_survives_json_round_trip(tmp_path):
    trace = model_check.explore(
        PROTOCOLS["lease"], max_schedules=25, seed=0, mutation="skip_fence"
    )
    path = str(tmp_path / "trace.json")
    model_check.save_trace(trace, path)
    loaded = model_check.load_trace(path)
    assert loaded == json.loads(json.dumps(trace))  # JSON-clean, no lossy types
    got = model_check.replay(loaded)
    assert got["detail"] == trace["failure"]["detail"]
    assert got["schedule"] == trace["failure"]["schedule"]


def test_same_seed_same_exploration():
    a = model_check.explore(PROTOCOLS["admission"], max_schedules=6, seed=3,
                            mutation="if_not_while")
    b = model_check.explore(PROTOCOLS["admission"], max_schedules=6, seed=3,
                            mutation="if_not_while")
    assert a == b  # schedulesExplored AND the full failure record
    c = model_check.explore(PROTOCOLS["admission"], max_schedules=6, seed=4,
                            mutation="if_not_while")
    # a different seed may catch on a different schedule — what must hold
    # is that it still catches within budget
    assert c["failure"] is not None


def test_cli_mc_gate(capsys):
    import pinot_tpu.tools.cli as cli

    rc = cli.main(["mc", "--mutations"])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
    assert "all gates green" in out.err
    assert "MISSED" not in out.out and "FAIL " not in out.out


def test_cli_mc_capture_then_replay(tmp_path, capsys):
    import pinot_tpu.tools.cli as cli

    path = str(tmp_path / "trace.json")
    rc = cli.main(["mc", "--mutations", "--protocols", "lease", "--save-trace", path])
    capsys.readouterr()
    assert rc == 0
    rc = cli.main(["mc", "--replay", path])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
    assert "reproduced lease[skip_fence]" in out.out


def test_provider_restored_after_schedules():
    model_check.run_schedule(PROTOCOLS["batcher"], seed=1)
    assert threads.provider() is threads._DEFAULT
    # and real primitives work immediately after a checker run
    ev = threads.Event()
    ev.set()
    assert ev.wait(timeout=0.1)
