"""Tier-1 CI gate: `pinot_tpu lint` must exit clean on the shipped tree.

The gate now covers the full pipeline — per-file rules plus the
interprocedural race detector and sync auditor with the committed
baseline — and budgets its wall time so the analysis can't quietly grow
past what a pre-merge check can afford.  Kept as its own tiny module so
the gate shows up as named tests in the standard tier-1 run (ROADMAP
command unchanged)."""
import json
import time

import pinot_tpu.tools.cli as cli


def test_cli_lint_exits_zero(capsys):
    rc = cli.main(["lint"])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
    assert "0 finding(s)" in out.err


def test_interprocedural_gate_clean_and_under_budget():
    from pinot_tpu.analysis.engine import run_project

    t0 = time.monotonic()
    report = run_project()
    elapsed = time.monotonic() - t0
    assert report.findings == [], "\n".join(str(f) for f in report.findings)
    assert report.stale_baseline == [], report.stale_baseline
    assert report.baselined > 0  # the committed baseline is live, not decorative
    # budget tracks tree growth: ~8s on an idle machine at r18 (the r10
    # original was 10s over a tree half this size); the gate is against
    # pathological blowup, not linear growth
    assert elapsed < 20.0, f"analysis gate took {elapsed:.1f}s (budget 20s)"


def test_cli_lint_json_report(capsys):
    rc = cli.main(["lint", "--json"])
    out = capsys.readouterr()
    assert rc == 0
    payload = json.loads(out.out)
    assert payload["count"] == 0 and payload["findings"] == []
    assert payload["staleBaseline"] == []
    assert payload["baselined"] > 0
    # the model-check sweep rides along in the one machine-readable gate
    mc = payload["modelCheck"]
    assert mc["ok"] is True
    assert set(mc["protocols"]) == {"admission", "batcher", "knobs", "lease", "residency"}
    for entry in mc["protocols"].values():
        assert entry["failure"] is None


def test_cli_lint_flags_bad_path(tmp_path, capsys):
    bad = tmp_path / "cluster" / "racy.py"
    bad.parent.mkdir()
    bad.write_text(
        "class C:\n"
        "    def bump(self):\n"
        "        self._n += 1\n"
    )
    rc = cli.main(["lint", str(bad), "--explain"])
    out = capsys.readouterr()
    assert rc == 1
    assert "W004" in out.out and "1 finding(s)" in out.err
