"""Tier-1 CI gate: `pinot_tpu lint` must exit clean on the shipped tree.

Kept as its own tiny module so the gate shows up as one named test in the
standard tier-1 run (ROADMAP command unchanged)."""
import pinot_tpu.tools.cli as cli


def test_cli_lint_exits_zero(capsys):
    rc = cli.main(["lint"])
    out = capsys.readouterr()
    assert rc == 0, out.out + out.err
    assert "0 finding(s)" in out.err


def test_cli_lint_flags_bad_path(tmp_path, capsys):
    bad = tmp_path / "cluster" / "racy.py"
    bad.parent.mkdir()
    bad.write_text(
        "class C:\n"
        "    def bump(self):\n"
        "        self._n += 1\n"
    )
    rc = cli.main(["lint", str(bad), "--explain"])
    out = capsys.readouterr()
    assert rc == 1
    assert "W004" in out.out and "1 finding(s)" in out.err
