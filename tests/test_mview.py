"""Materialized view tests: refresh, staleness, rewrite, fallback."""
import numpy as np
import pytest

from pinot_tpu.cluster import Broker, Coordinator, ServerInstance
from pinot_tpu.cluster.mview import MaterializedView, MaterializedViewManager
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import SegmentsConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows

T0 = 1_700_000_000_000
DAY = 86_400_000


def _schema():
    return Schema(
        "events",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("day_ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )


def _cluster():
    coord = Coordinator(replication=1)
    coord.register_server(ServerInstance("s0"))
    coord.add_table(_schema(), TableConfig(name="events", segments=SegmentsConfig(time_column="day_ts")))
    return coord


def _seg(coord, name, day, seed, n=500):
    rng = np.random.default_rng(seed)
    data = {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "day_ts": np.full(n, T0 + day * DAY, dtype=np.int64),
        "v": rng.integers(0, 100, n),
    }
    cfg = coord.tables["events"].config
    coord.add_segment("events", build_segment(_schema(), data, name, table_config=cfg))
    return data


@pytest.fixture()
def env():
    coord = _cluster()
    _seg(coord, "d0", day=0, seed=1)
    _seg(coord, "d1", day=1, seed=2)
    mgr = MaterializedViewManager(coord)
    mv = MaterializedView(
        name="events_daily",
        source_table="events",
        dimensions=["city", "day_ts"],
        metrics=[("count", "*"), ("sum", "v"), ("max", "v")],
        time_column="day_ts",
    )
    mgr.create_view(mv)
    return coord, mgr


QUERY = "SELECT city, COUNT(*), SUM(v), MAX(v) FROM events GROUP BY city ORDER BY city"


class TestRefreshAndRewrite:
    def test_refresh_then_rewrite_matches_source(self, env):
        coord, mgr = env
        report = mgr.refresh("events_daily")
        assert len(report["refreshedBuckets"]) == 2  # two days
        direct = Broker(coord).query(QUERY)
        via_mv = mgr.query(QUERY)
        assert via_mv.stats.mv_rewrite is True
        assert_same_rows(via_mv.rows, direct.rows, ordered=True)
        # the MV scanned collapsed rows, far fewer than the source
        assert via_mv.stats.num_docs_scanned < direct.stats.num_docs_scanned

    def test_stale_bucket_falls_back(self, env):
        coord, mgr = env
        mgr.refresh("events_daily")
        _seg(coord, "d1b", day=1, seed=3)  # new source data -> bucket 1 stale
        assert len(mgr.stale_buckets("events_daily")) == 1
        res = mgr.query(QUERY)
        assert res.stats.mv_rewrite is False  # fell back to the source
        assert_same_rows(res.rows, Broker(coord).query(QUERY).rows, ordered=True)
        # refresh repairs only the stale bucket, then rewrite resumes
        report = mgr.refresh("events_daily")
        assert len(report["refreshedBuckets"]) == 1
        assert mgr.stale_buckets("events_daily") == []
        res2 = mgr.query(QUERY)
        assert res2.stats.mv_rewrite is True
        assert_same_rows(res2.rows, Broker(coord).query(QUERY).rows, ordered=True)

    def test_filter_on_dimension_rewrites(self, env):
        coord, mgr = env
        mgr.refresh("events_daily")
        sql = "SELECT city, SUM(v) FROM events WHERE city IN ('sf', 'la') GROUP BY city ORDER BY city"
        res = mgr.query(sql)
        assert res.stats.mv_rewrite is True
        assert_same_rows(res.rows, Broker(coord).query(sql).rows, ordered=True)

    def test_unmatched_shapes_fall_back(self, env):
        coord, mgr = env
        mgr.refresh("events_daily")
        # AVG is not a stored metric; filter on a non-dim; group on non-dim
        for sql in [
            "SELECT city, AVG(v) FROM events GROUP BY city",
            "SELECT city, SUM(v) FROM events WHERE v > 50 GROUP BY city",
        ]:
            res = mgr.query(sql)
            assert res.stats.mv_rewrite is False
            assert_same_rows(res.rows, Broker(coord).query(sql).rows)
