"""Crash-safe cluster tests: durable control plane, restart recovery, and
live rebalance — driven by the deterministic crash harness (named
kill-points inside every commit protocol + scripted server crash/restart).

The contract under test (ISSUE 8):
  * a coordinator rebuilt over the same meta_dir has IDENTICAL ideal state,
    segment metadata, replica-group membership, and realtime checkpoint
    pointers;
  * a crash at ANY named kill-point of a commit path (segment write, seal,
    deep-store upload, checkpoint, journal append, snapshot compaction,
    rebalance move) loses no committed rows and double-counts none;
  * servers restarted after a crash re-download committed segments from the
    deep store (CRC-verified) and broker routing heals;
  * rebalance moves segments under query load without ever dropping below
    the min-available-replicas floor.
"""
import json
import os

import numpy as np
import pytest

from pinot_tpu.cluster import (
    Broker,
    Coordinator,
    FaultPlan,
    SegmentDeepStore,
    ServerInstance,
)
from pinot_tpu.cluster.journal import MetaJournal
from pinot_tpu.realtime.manager import RealtimeTableDataManager
from pinot_tpu.realtime.stream import InMemoryStream
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.store import SegmentCorruptError, verify_segment
from pinot_tpu.spi.config import SegmentsConfig, StreamConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.utils import crashpoints
from pinot_tpu.utils.crashpoints import InjectedCrash

from golden import assert_same_rows, sqlite_from_data


@pytest.fixture(autouse=True)
def _clean_kill_points():
    crashpoints.reset()
    yield
    crashpoints.reset()


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _data(n, seed, t0=1_700_000_000_000):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "v": rng.integers(0, 100, n),
        "ts": t0 + rng.integers(0, 86_400_000, n).astype(np.int64),
    }


def _durable_cluster(tmp_path, n_servers=3, replication=2, n_segments=4, rows=200):
    """Deterministic cluster with journal + deep store: same args -> same
    assignment, data, and on-disk layout."""
    coord = Coordinator(
        replication=replication,
        meta_dir=str(tmp_path / "meta"),
        deep_store=str(tmp_path / "deep"),
    )
    for i in range(n_servers):
        coord.register_server(
            ServerInstance(f"server{i}", data_dir=str(tmp_path / f"server{i}"))
        )
    coord.add_table(_schema(), TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    datas = []
    for i in range(n_segments):
        d = _data(rows, seed=100 + i)
        datas.append(d)
        seg = build_segment(
            _schema(), d, f"seg{i}", output_dir=str(tmp_path / "build" / f"seg{i}")
        )
        coord.add_segment("t", seg)
    merged = {k: np.concatenate([d[k] for d in datas]) for k in datas[0]}
    return coord, merged


QUERIES = [
    "SELECT COUNT(*), SUM(v) FROM t",
    "SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city ORDER BY city",
]


def _ideal_fingerprint(coord, table="t"):
    meta = coord.tables[table]
    return {
        "ideal": {seg: sorted(srvs) for seg, srvs in meta.ideal.items()},
        "numDocs": {seg: m["numDocs"] for seg, m in meta.segment_meta.items()},
        "timeRange": {
            seg: tuple(m["timeRange"]) if m.get("timeRange") else None
            for seg, m in meta.segment_meta.items()
        },
        "groups": dict(coord.replica_group),
        "replication": coord.replication,
    }


class TestCoordinatorJournal:
    def test_restart_rebuilds_identical_ideal_state(self, tmp_path):
        coord, _ = _durable_cluster(tmp_path)
        before = _ideal_fingerprint(coord)
        coord2 = Coordinator(meta_dir=str(tmp_path / "meta"), deep_store=str(tmp_path / "deep"))
        assert _ideal_fingerprint(coord2) == before
        # routing view is rebuildable too once servers re-register
        for i in range(3):
            coord2.register_server(
                ServerInstance(f"server{i}", data_dir=str(tmp_path / f"server{i}"))
            )
        assert coord2.external_view("t") == coord.external_view("t")

    def test_snapshot_compaction_roundtrip(self, tmp_path):
        coord, _ = _durable_cluster(tmp_path)
        before = _ideal_fingerprint(coord)
        coord.checkpoint_metadata()  # compacts: snapshot written, journal truncated
        assert os.path.getsize(tmp_path / "meta" / "journal.jsonl") == 0
        coord2 = Coordinator(meta_dir=str(tmp_path / "meta"))
        assert _ideal_fingerprint(coord2) == before

    def test_torn_journal_tail_is_dropped_not_fatal(self, tmp_path):
        coord, _ = _durable_cluster(tmp_path)
        before = _ideal_fingerprint(coord)
        path = tmp_path / "meta" / "journal.jsonl"
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"seq": 9999, "op": "set_ideal", "table": "t", "segm')  # torn append
        coord2 = Coordinator(meta_dir=str(tmp_path / "meta"))
        assert _ideal_fingerprint(coord2) == before

    def test_corrupt_snapshot_quarantined_and_bak_used(self, tmp_path):
        coord, _ = _durable_cluster(tmp_path)
        coord.checkpoint_metadata()
        before = _ideal_fingerprint(coord)
        # second compaction: snapshot.json.bak now holds the same state
        coord.checkpoint_metadata()
        snap = tmp_path / "meta" / "snapshot.json"
        with open(snap, "w", encoding="utf-8") as f:
            f.write("{ not json")
        coord2 = Coordinator(meta_dir=str(tmp_path / "meta"))
        assert _ideal_fingerprint(coord2) == before
        assert any(p.name.startswith("snapshot.json.corrupt") for p in (tmp_path / "meta").iterdir())

    @pytest.mark.parametrize(
        "point",
        [
            "journal.snapshot.after_bak",
            "journal.snapshot.after_write",
            "journal.snapshot.before_truncate",
        ],
    )
    def test_crash_mid_compaction_recovers(self, tmp_path, point):
        """Compaction dying between ANY two steps (bak swap / snapshot
        write / journal truncate) must leave a state the next boot rebuilds
        exactly — idempotent replay covers the snapshot/journal overlap."""
        coord, _ = _durable_cluster(tmp_path)
        coord.checkpoint_metadata()  # ensure a previous snapshot exists
        coord.add_segment(
            "t",
            build_segment(_schema(), _data(50, seed=999), "seg_late",
                          output_dir=str(tmp_path / "build" / "seg_late")),
        )
        before = _ideal_fingerprint(coord)
        crashpoints.arm(point)
        with pytest.raises(InjectedCrash):
            coord.checkpoint_metadata()
        coord2 = Coordinator(meta_dir=str(tmp_path / "meta"))
        assert _ideal_fingerprint(coord2) == before

    @pytest.mark.parametrize(
        "point,committed",
        [
            # death after upload but before the journal append: assignment
            # never committed — the restarted coordinator must NOT know the
            # segment (the deep-store copy is harmless orphan data)
            ("coordinator.add_segment.after_upload", False),
            # death after the journal append: assignment IS committed — the
            # restarted coordinator must serve it (servers reconcile it in)
            ("coordinator.add_segment.after_journal", True),
        ],
    )
    def test_crash_mid_add_segment(self, tmp_path, point, committed):
        coord, _ = _durable_cluster(tmp_path)
        seg = build_segment(_schema(), _data(50, seed=999), "seg_late",
                            output_dir=str(tmp_path / "build" / "seg_late"))
        crashpoints.arm(point)
        with pytest.raises(InjectedCrash):
            coord.add_segment("t", seg)
        coord2 = Coordinator(meta_dir=str(tmp_path / "meta"), deep_store=str(tmp_path / "deep"))
        assert ("seg_late" in coord2.tables["t"].ideal) == committed
        servers = [ServerInstance(f"server{i}", data_dir=str(tmp_path / f"server{i}"))
                   for i in range(3)]
        for s in servers:
            coord2.register_server(s)
        if committed:
            # reconciliation completed the half-done placement from deep store
            holders = [s for s in servers if s.get_segment("t", "seg_late") is not None]
            assert sorted(s.name for s in holders) == sorted(coord2.tables["t"].ideal["seg_late"])
        # either way the cluster serves consistent results afterwards
        res = Broker(coord2).query("SELECT COUNT(*) FROM t")
        expected = 4 * 200 + (50 if committed else 0)
        assert res.rows[0][0] == expected

    def test_journal_append_killpoint_loses_only_uncommitted_tail(self, tmp_path):
        coord, _ = _durable_cluster(tmp_path)
        before = _ideal_fingerprint(coord)
        crashpoints.arm("journal.append.after_write")
        with pytest.raises(InjectedCrash):
            coord.add_table(
                Schema("t2", [FieldSpec("x", DataType.LONG, role=FieldRole.METRIC)]),
                TableConfig(name="t2"),
            )
        coord2 = Coordinator(meta_dir=str(tmp_path / "meta"))
        # the torn append never committed; prior state intact
        assert "t2" not in coord2.tables
        assert _ideal_fingerprint(coord2) == before


class TestServerCrashRestart:
    def test_crash_then_restart_restores_from_deep_store(self, tmp_path):
        coord, merged = _durable_cluster(tmp_path)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        conn = sqlite_from_data("t", merged)
        baseline = {sql: broker.query(sql).rows for sql in QUERIES}

        victim = coord.servers["server0"]
        owned = set(victim.segment_names("t"))
        assert owned, "victim must own segments for the test to bite"
        coord.crash_server("server0")
        assert victim.crashed and victim.segments == {}
        # cluster still serves (replication=2) and matches golden
        for sql in QUERIES:
            res = broker.query(sql)
            assert_same_rows(res.rows, baseline[sql])
            assert_same_rows(res.rows, conn.execute(sql).fetchall())

        stats = coord.restart_server("server0")
        assert stats["restored"] == len(owned) and stats["missing"] == 0
        assert set(victim.segment_names("t")) == owned
        assert "server0" in coord.live
        for sql in QUERIES:
            assert_same_rows(broker.query(sql).rows, baseline[sql])

    def test_restart_heals_broker_breaker(self, tmp_path):
        """mark_up from restart_server resets the broker's circuit breaker
        (the live-listener path) so the recovered server serves again."""
        coord, _ = _durable_cluster(tmp_path, n_servers=2, replication=2)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        coord.crash_server("server0")
        broker.query(QUERIES[0])  # routes around the dead server
        coord.restart_server("server0")
        res = broker.query(QUERIES[0])
        assert res.stats.partial_result is False
        assert broker.health.available("server0")

    def test_corrupt_local_copy_heals_on_restart(self, tmp_path):
        """A flipped byte in a server's local copy fails CRC on restart and
        the segment re-downloads from the deep store."""
        coord, _ = _durable_cluster(tmp_path)
        srv = coord.servers["server0"]
        seg_name = sorted(srv.segment_names("t"))[0]
        coord.crash_server("server0")
        local = os.path.join(srv.data_dir, "t", seg_name)
        assert not os.path.isdir(local)  # lazily downloaded on first restore
        coord.restart_server("server0")
        assert os.path.isdir(local)
        with open(os.path.join(local, "columns.bin"), "r+b") as f:
            b = f.read(1)
            f.seek(0)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(SegmentCorruptError):
            verify_segment(local)
        coord.crash_server("server0")
        coord.restart_server("server0")
        verify_segment(local)  # re-downloaded, CRC-clean
        assert os.path.isdir(local + ".corrupt")  # evidence quarantined
        assert srv.get_segment("t", seg_name) is not None

    def test_packed_fwd_region_crc_round_trip(self, tmp_path):
        """Bit-packed forward-index words sit inside the CRC envelope: the
        deep-store copy round-trips them bit-exactly, and a flipped byte in
        the packed `.fwd` region alone fails verify_segment."""
        from pinot_tpu.segment.segment import ImmutableSegment
        from pinot_tpu.segment.store import read_segment

        out = str(tmp_path / "seg")
        seg = build_segment(_schema(), _data(400, seed=11), "seg", output_dir=out)
        c = seg.column("city")
        assert c.code_bits == 4 and c.packed is not None  # card 3 -> 4-bit lanes
        verify_segment(out)
        loaded = ImmutableSegment.load(out, verify=True)
        np.testing.assert_array_equal(loaded.column("city").packed, c.packed)
        np.testing.assert_array_equal(loaded.column("city").codes, c.codes)

        meta, _ = read_segment(out)
        (reg,) = [r for r in meta["regions"] if r["name"] == "city.fwd"]
        bin_path = os.path.join(out, "columns.bin")
        with open(bin_path, "r+b") as f:
            f.seek(reg["offset"])
            b = f.read(1)
            f.seek(reg["offset"])
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(SegmentCorruptError):
            verify_segment(out)
        with open(bin_path, "r+b") as f:  # restore the byte -> clean again
            f.seek(reg["offset"])
            f.write(bytes([b[0]]))
        verify_segment(out)
        np.testing.assert_array_equal(
            ImmutableSegment.load(out, verify=True).column("city").packed, c.packed
        )

    def test_scripted_crash_restart_mid_workload(self, tmp_path):
        """FaultPlan lifecycle rules: server0 crashes when server1 takes its
        2nd call, restarts on server1's 4th — queries stay exact throughout."""
        coord, merged = _durable_cluster(tmp_path, n_servers=2, replication=2)
        conn = sqlite_from_data("t", merged)
        plan = (
            FaultPlan(seed=3)
            .crash_server("server0", on_call=2, of="server1")
            .restart_server("server0", on_call=4, of="server1")
            .attach(coord)
        )
        broker = Broker(coord)
        broker._sleep = lambda s: None
        for round_ in range(6):
            for sql in QUERIES:
                assert_same_rows(broker.query(sql).rows, conn.execute(sql).fetchall())
        kinds = [k for (_, _, k, _) in plan.log]
        assert "crash" in kinds and "restart" in kinds
        assert not coord.servers["server0"].crashed


class TestSegmentCommitKillPoints:
    SEAL_POINTS = [
        "segment.write.after_data_write",
        "segment.write.after_data_replace",
        "segment.write.meta.after_write",
        "segment.write.meta.after_replace",
        "segment.seal.after_build",
        "deepstore.upload.before_commit",
        "deepstore.upload.after_commit",
        "segment.seal.after_upload",
        "segment.seal.after_swap",
        "realtime.checkpoint.after_write",
        "realtime.checkpoint.after_bak",
        "realtime.checkpoint.after_replace",
    ]

    @pytest.mark.parametrize("point", SEAL_POINTS)
    def test_crash_at_every_seal_step_loses_nothing(self, tmp_path, point):
        """Kill the seal/commit protocol at EVERY named step: after restart
        the table must hold exactly the published rows — none lost, none
        double-counted — because the checkpoint only advances after the
        durable build + upload, and replay re-consumes uncommitted rows."""
        schema = _schema()
        cfg = TableConfig(
            name="t", stream=StreamConfig(stream_type="memory", max_rows_per_segment=16)
        )
        stream = InMemoryStream(num_partitions=1)
        rows = _data(50, seed=11)
        for i in range(50):
            stream.publish({k: rows[k][i] for k in rows}, partition=0)
        deep = SegmentDeepStore(str(tmp_path / "deep"))
        mgr = RealtimeTableDataManager(
            schema, cfg, str(tmp_path / "rt"), stream=stream, deep_store=deep
        )
        crashpoints.arm(point)
        with pytest.raises(InjectedCrash):
            mgr.consume_all()
        assert crashpoints.fired and crashpoints.fired[-1][0] == point
        # restart: a fresh manager over the same dirs replays the committed
        # checkpoint and re-consumes everything after it
        mgr2 = RealtimeTableDataManager(
            schema, cfg, str(tmp_path / "rt"), stream=stream, deep_store=deep
        )
        mgr2.consume_all()
        assert mgr2.total_rows == 50
        v = sum(int(s.column("v").decoded().sum()) for s in mgr2.query_segments())
        assert v == int(rows["v"].sum())

    def test_checkpoint_pointer_journaled_by_coordinator(self, tmp_path):
        schema = _schema()
        cfg = TableConfig(
            name="rt", stream=StreamConfig(stream_type="memory", max_rows_per_segment=16)
        )
        stream = InMemoryStream(num_partitions=2)
        for i in range(60):
            stream.publish({"city": "sf", "v": i, "ts": 1_700_000_000_000 + i}, key=f"k{i}")
        coord = Coordinator(
            replication=1, meta_dir=str(tmp_path / "meta"), deep_store=str(tmp_path / "deep")
        )
        mgr = coord.add_realtime_table(schema, cfg, str(tmp_path / "rt"), stream=stream)
        coord.run_realtime_consumption()
        assert mgr.total_rows == 60
        committed = {
            p: dict(cp) for p, cp in coord.rt_checkpoints["rt"].items()
        }
        assert committed, "seals must journal checkpoint pointers"
        # a restarted coordinator knows the pointers WITHOUT the data dir,
        # and recover_realtime resumes from them with no lost/dup rows
        coord2 = Coordinator(meta_dir=str(tmp_path / "meta"), deep_store=str(tmp_path / "deep"))
        assert coord2.rt_checkpoints["rt"] == committed
        mgr2 = coord2.recover_realtime("rt", stream=stream)
        coord2.run_realtime_consumption()
        assert mgr2.total_rows == 60
        # on-disk checkpoint agrees with the journaled pointers
        with open(tmp_path / "rt" / "checkpoint.json", encoding="utf-8") as f:
            disk = json.load(f)
        for p, cp in committed.items():
            assert disk[str(p)]["offset"] == cp["offset"]
            assert disk[str(p)]["seq"] == cp["seq"]


class TestLiveRebalance:
    def test_rebalance_load_before_drop_under_queries(self, tmp_path):
        """A new server joins; rebalance moves segments onto it while
        queries run at EVERY protocol step — the availability floor holds
        (every segment keeps >= min live replicas at add/commit/drop), and
        every interleaved query is exact."""
        coord, merged = _durable_cluster(tmp_path, n_servers=2, replication=2, n_segments=6)
        conn = sqlite_from_data("t", merged)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        baseline = {sql: conn.execute(sql).fetchall() for sql in QUERIES}
        new_server = ServerInstance("server_new", data_dir=str(tmp_path / "server_new"))
        coord.register_server(new_server)

        floors = []

        def probe(point):
            # runs at every rebalance kill-point: queries must stay exact
            # and no segment may drop below the floor, mid-move included
            for sql in QUERIES:
                assert_same_rows(broker.query(sql).rows, baseline[sql])
            view = coord.external_view("t")
            floors.append(min(len(v) for v in view.values()))

        import pinot_tpu.cluster.rebalance as rebalance_mod

        orig = rebalance_mod.crash_point
        rebalance_mod.crash_point = probe
        try:
            stats = coord.rebalance("t", min_available_replicas=1)
        finally:
            rebalance_mod.crash_point = orig
        assert stats["segmentsMoved"] > 0
        assert floors and min(floors) >= 1
        # moves landed on the new server and results still exact
        assert new_server.segment_names("t")
        for sql in QUERIES:
            assert_same_rows(broker.query(sql).rows, baseline[sql])
        # versioned view: the rebalance committed new routing epochs
        v1, view = coord.versioned_view("t")
        assert v1 > 0 and all(view.values())

    @pytest.mark.parametrize("point", ["rebalance.after_add", "rebalance.after_commit"])
    def test_crash_mid_rebalance_converges_on_restart(self, tmp_path, point):
        coord, merged = _durable_cluster(tmp_path, n_servers=2, replication=2, n_segments=6)
        conn = sqlite_from_data("t", merged)
        coord.register_server(
            ServerInstance("server_new", data_dir=str(tmp_path / "server_new"))
        )
        crashpoints.arm(point)
        with pytest.raises(InjectedCrash):
            coord.rebalance("t")
        # coordinator restarts from its journal; servers re-register and
        # reconcile — stale copies drop, committed moves complete
        coord2 = Coordinator(meta_dir=str(tmp_path / "meta"), deep_store=str(tmp_path / "deep"))
        servers = [
            ServerInstance(n, data_dir=str(tmp_path / n))
            for n in ("server0", "server1", "server_new")
        ]
        for s in servers:
            coord2.register_server(s)
        # every ideal assignment is actually served
        for seg, assigned in coord2.tables["t"].ideal.items():
            for name in assigned:
                assert coord2.servers[name].get_segment("t", seg) is not None
        broker = Broker(coord2)
        broker._sleep = lambda s: None
        for sql in QUERIES:
            assert_same_rows(broker.query(sql).rows, conn.execute(sql).fetchall())
        # finishing the rebalance converges (idempotent)
        coord2.rebalance("t")
        for sql in QUERIES:
            assert_same_rows(broker.query(sql).rows, conn.execute(sql).fetchall())


class TestLifecycleChaosAcceptance:
    def test_lifecycle_chaos_end_to_end(self, tmp_path):
        """ISSUE 8 acceptance: seeded FaultPlan crashes/restarts servers
        mid-scatter, the coordinator itself dies mid-assignment (kill-point)
        and restarts from its journal, and a rebalance runs between query
        rounds — every query either succeeds with results identical to the
        fault-free baseline or returns a structured partial/error response;
        after all restarts the ideal state, total rows, and stream offsets
        match the pre-crash committed state; the availability floor holds."""
        # fault-free baseline over identical data
        baseline_coord, merged = _durable_cluster(
            tmp_path / "base", n_servers=3, replication=2, n_segments=5
        )
        conn = sqlite_from_data("t", merged)
        baseline = {sql: Broker(baseline_coord).query(sql).rows for sql in QUERIES}
        for sql in QUERIES:
            assert_same_rows(baseline[sql], conn.execute(sql).fetchall())

        # chaos cluster: same data, lifecycle fault plan attached
        coord, _ = _durable_cluster(tmp_path / "chaos", n_servers=3, replication=2, n_segments=5)
        plan = (
            FaultPlan(seed=42)
            .crash_server("server0", on_call=2, of="server1")
            .restart_server("server0", on_call=5, of="server1")
            .crash_server("server2", on_call=7, of="server1")
            .restart_server("server2", on_call=9, of="server1")
            .attach(coord)
        )
        broker = Broker(coord)
        broker._sleep = lambda s: None
        total_sql = "SET allowPartialResults = true; SELECT COUNT(*), SUM(v) FROM t"

        ok = partial = 0
        for round_ in range(8):
            for sql in QUERIES:
                res = broker.query("SET allowPartialResults = true; " + sql)
                if res.stats.partial_result:
                    # structured degradation: exceptions recorded, not wrong rows
                    partial += 1
                    assert res.stats.exceptions
                else:
                    ok += 1
                    assert_same_rows(res.rows, baseline[sql])
            # floor invariant after every round: with both crash targets
            # never down at once, every segment keeps >= 1 live replica
            view = coord.external_view("t")
            assert min(len(v) for v in view.values()) >= 1
        assert ok > 0
        kinds = [k for (_, _, k, _) in plan.log]
        assert kinds.count("crash") == 2 and kinds.count("restart") == 2

        # rebalance under the recovered topology, then exactness again
        coord.rebalance("t")
        for sql in QUERIES:
            assert_same_rows(broker.query(sql).rows, baseline[sql])

        # --- coordinator crash mid-assignment, restart from journal -------
        seg = build_segment(
            _schema(), _data(80, seed=777), "seg_chaos",
            output_dir=str(tmp_path / "chaos" / "build" / "seg_chaos"),
        )
        pre_crash = _ideal_fingerprint(coord)
        crashpoints.arm("coordinator.add_segment.after_journal")
        with pytest.raises(InjectedCrash):
            coord.add_segment("t", seg)

        coord2 = Coordinator(
            meta_dir=str(tmp_path / "chaos" / "meta"),
            deep_store=str(tmp_path / "chaos" / "deep"),
        )
        # identical committed control-plane state: everything from before the
        # crash, plus the journaled (committed) assignment of seg_chaos
        restored = _ideal_fingerprint(coord2)
        assert "seg_chaos" in coord2.tables["t"].ideal
        assert restored["numDocs"].pop("seg_chaos") == 80
        for key in ("ideal", "timeRange"):
            restored[key].pop("seg_chaos")
        assert restored == pre_crash
        for i in range(3):
            coord2.register_server(
                ServerInstance(f"server{i}", data_dir=str(tmp_path / "chaos" / f"server{i}"))
            )
        broker2 = Broker(coord2)
        broker2._sleep = lambda s: None
        res = broker2.query("SELECT COUNT(*), SUM(v) FROM t")
        assert res.rows[0][0] == 5 * 200 + 80  # committed rows, exactly once
        assert res.stats.partial_result is False
        # floor invariant on the rebuilt cluster
        view = coord2.external_view("t")
        assert min(len(v) for v in view.values()) >= 1
