"""JAX-aware repo lint (pinot_tpu.analysis.repo_lint).

Each rule fires on a minimal fixture snippet and stays quiet on the
locked/hoisted counterpart; the live pinot_tpu tree must be clean."""
import textwrap

from pinot_tpu.analysis.repo_lint import Finding, lint_source, lint_tree


def _lint(src, threaded=False):
    return lint_source(textwrap.dedent(src), path="fixture.py", threaded=threaded)


def _rules(src, threaded=False):
    return [f.rule for f in _lint(src, threaded=threaded)]


class TestW001FloatLiteralInKernel:
    def test_flags_float_literal_in_jitted_arithmetic(self):
        src = """
        import jax

        def kernel(x):
            return x * 0.5

        fn = jax.jit(kernel)
        """
        assert _rules(src) == ["W001"]

    def test_flags_float_comparison_under_decorator(self):
        src = """
        import jax

        @jax.jit
        def kernel(x):
            return x > 1.5
        """
        assert _rules(src) == ["W001"]

    def test_quiet_outside_kernels_and_on_int_literals(self):
        src = """
        import jax

        def helper(x):
            return x * 0.5  # not jitted: host-side is fine

        def kernel(x):
            return x * 2

        fn = jax.jit(kernel)
        """
        assert _rules(src) == []


class TestW002HostSyncInKernel:
    def test_flags_item_and_np_asarray(self):
        src = """
        import jax
        import numpy as np

        def kernel(x):
            n = x.sum().item()
            return np.asarray(x) + n

        fn = jax.jit(kernel)
        """
        assert _rules(src) == ["W002", "W002"]

    def test_quiet_on_jnp_asarray(self):
        src = """
        import jax
        import jax.numpy as jnp

        def kernel(x):
            return jnp.asarray(x)

        fn = jax.jit(kernel)
        """
        assert _rules(src) == []


class TestW002PallasKernelAndLaunchLoop:
    def test_flags_host_numpy_inside_pallas_kernel_body(self):
        src = """
        import numpy as np
        from jax.experimental import pallas as pl

        def scan_kernel(x_ref, o_ref):
            o_ref[...] = np.cumsum(x_ref[...])

        def run(x):
            return pl.pallas_call(scan_kernel, out_shape=x)(x)
        """
        assert _rules(src) == ["W002"]

    def test_quiet_on_np_outside_kernel_body(self):
        src = """
        import numpy as np
        from jax.experimental import pallas as pl

        def scan_kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...]

        def run(x):
            shape = np.zeros(4)  # host setup around the launch is fine
            return pl.pallas_call(scan_kernel, out_shape=x)(x)
        """
        assert _rules(src) == []

    def test_flags_block_until_ready_in_launch_loop(self):
        src = """
        def run(fn, batches):
            outs = []
            for cols, params in batches:
                outs.append(fn(cols, params).block_until_ready())
            return outs
        """
        assert _rules(src) == ["W002"]

    def test_quiet_on_hoisted_sync_and_device_get_in_loop(self):
        src = """
        import jax

        def run(fn, batches):
            outs = [fn(c, p) for c, p in batches]
            for o in outs:
                jax.device_get(o)  # fetch is a completion fence, not a stall
            return outs[-1].block_until_ready()
        """
        assert _rules(src) == []


class TestW003JitInLoop:
    def test_flags_jit_inside_loop_body(self):
        src = """
        import jax

        def run(fns, x):
            outs = []
            for f in fns:
                outs.append(jax.jit(f)(x))
            return outs
        """
        assert "W003" in _rules(src)

    def test_quiet_when_hoisted(self):
        src = """
        import jax

        def run(f, xs):
            g = jax.jit(f)
            return [g(x) for x in xs]
        """
        assert _rules(src) == []

    def test_def_inside_loop_resets_scope(self):
        src = """
        import jax

        for name in ("a", "b"):
            def make(f):
                return jax.jit(f)
        """
        assert _rules(src) == []


class TestW004UnlockedSharedRMW:
    def test_flags_augassign_on_self_attr(self):
        src = """
        class Broker:
            def route(self):
                self._rr += 1
        """
        assert _rules(src, threaded=True) == ["W004"]

    def test_flags_alias_bucket_write(self):
        # the exact broker token-bucket race shape from ADVICE r5
        src = """
        class Quota:
            def check(self, table):
                b = self._buckets.get(table)
                b[0] = b[0] - 1
        """
        assert _rules(src, threaded=True) == ["W004"]

    def test_quiet_under_lock(self):
        src = """
        class Broker:
            def route(self):
                with self._lock:
                    self._rr += 1
        """
        assert _rules(src, threaded=True) == []

    def test_quiet_on_plain_insert_and_init(self):
        src = """
        class Broker:
            def __init__(self):
                self._rr = 0

            def register(self, name, server):
                self.servers[name] = server
        """
        assert _rules(src, threaded=True) == []

    def test_w004_requires_threaded_scope(self):
        src = """
        class Planner:
            def bump(self):
                self._n += 1
        """
        assert _rules(src, threaded=False) == []


class TestW005WallClockInElapsedMath:
    def test_flags_time_time_subtraction(self):
        src = """
        import time

        def age(started):
            return time.time() - started
        """
        assert _rules(src) == ["W005"]

    def test_flags_aliased_wall_clock_in_comparison(self):
        src = """
        import time

        def expired(deadline):
            now = time.time()
            return now >= deadline
        """
        assert _rules(src) == ["W005"]

    def test_quiet_on_monotonic_and_epoch_stamps(self):
        src = """
        import time

        def age(started):
            return time.monotonic() - started

        def creation_time_ms():
            return int(time.time() * 1000)

        def stamp():
            return time.time()
        """
        assert _rules(src) == []

    def test_alias_in_other_scope_does_not_leak(self):
        src = """
        import time

        def stamp():
            now = time.time()
            return now

        def age(now, started):
            return now - started
        """
        assert _rules(src) == []


class TestW006SwallowedClusterException:
    def test_flags_except_continue_without_recording(self):
        src = """
        def scatter(servers):
            out = []
            for s in servers:
                try:
                    out.append(s.execute())
                except Exception:
                    continue
            return out
        """
        assert _rules(src, threaded=True) == ["W006"]

    def test_flags_silent_pass(self):
        src = """
        def drop(self, name):
            try:
                self._close(name)
            except Exception:
                pass
        """
        assert _rules(src, threaded=True) == ["W006"]

    def test_quiet_when_recorded_or_reraised(self):
        src = """
        import logging

        def scatter(self, servers):
            for s in servers:
                try:
                    s.execute()
                except KeyError:
                    raise
                except Exception:
                    logging.exception("server %s failed", s)
        """
        assert _rules(src, threaded=True) == []

    def test_w006_requires_cluster_scope(self):
        src = """
        def best_effort(x):
            try:
                return int(x)
            except ValueError:
                pass
        """
        assert _rules(src, threaded=False) == []


class TestW007UnboundedMetricName:
    def test_flags_sql_in_counter_name(self):
        src = """
        def record(self, sql):
            METRICS.counter(f"latency.{sql}").inc()
        """
        assert _rules(src) == ["W007"]

    def test_flags_query_id_in_span_name(self):
        src = """
        def run(self, trace, query_id):
            with trace.span(f"exec:{query_id}"):
                pass
        """
        assert _rules(src) == ["W007"]

    def test_flags_attribute_access_and_bare_id(self):
        src = """
        def run(self, ctx):
            METRICS.histogram(f"lat.{ctx.fingerprint}").update(1)
            METRICS.gauge(f"g.{id}").set(1)
        """
        assert _rules(src) == ["W007", "W007"]

    def test_quiet_on_bounded_label_spaces(self):
        src = """
        def record(self, table, server, seg):
            METRICS.gauge(f"server.segmentBytes.{table}").add(1)
            METRICS.counter(f"broker.breakerOpen.{server}").inc()
            with self.trace.span(f"launch:{seg.name}"):
                pass
        """
        assert _rules(src) == []

    def test_quiet_on_plain_string_names_and_non_sinks(self):
        src = """
        def record(self, sql):
            METRICS.counter("broker.queries").inc()
            log(f"ran {sql}")  # not a metric/span name sink
        """
        assert _rules(src) == []


class TestW008LiteralFingerprintInPlanCacheKey:
    def test_flags_fingerprint_in_cache_get_key(self):
        src = """
        def plan(self, ctx, seg):
            return self._plan_cache.get((ctx.fingerprint(), seg.signature()))
        """
        assert _rules(src) == ["W008"]

    def test_flags_fingerprint_via_key_alias(self):
        src = """
        def plan(ctx, seg):
            key = (ctx.fingerprint(), seg.signature())
            cached = _PLAN_CACHE.get(key)
            return cached
        """
        assert _rules(src) == ["W008"]

    def test_flags_subscript_store(self):
        src = """
        def plan(self, ctx, plan):
            self._plan_cache[ctx.fingerprint()] = plan
        """
        assert _rules(src) == ["W008"]

    def test_quiet_on_shape_fingerprint_key(self):
        src = """
        def plan(self, ctx, seg):
            key = (ctx.shape_fingerprint(), seg.signature())
            return self._plan_cache.get(key)
        """
        assert _rules(src) == []

    def test_quiet_on_non_plan_cache_sinks(self):
        src = """
        def execute(self, ctx, table):
            ckey = (table, ctx.fingerprint())
            hit = self.result_cache.get(ckey)
            self.slow_queries.record(ctx.sql, ctx.fingerprint())
            return hit
        """
        assert _rules(src) == []

    def test_alias_in_other_scope_does_not_leak(self):
        src = """
        def make_key(ctx):
            key = ctx.fingerprint()
            return key

        def plan(self, key):
            return self._plan_cache.get(key)
        """
        assert _rules(src) == []


class TestW015UnboundedServingGrowth:
    def test_flags_list_append_in_serving_method(self):
        src = """
        class Broker:
            def __init__(self):
                self.audit = []

            def execute(self, ctx):
                self.audit.append(ctx.sql)
        """
        assert _rules(src, threaded=True) == ["W015"]

    def test_flags_dict_keyed_by_query_id(self):
        src = """
        class Broker:
            def __init__(self):
                self.results = {}

            def handle(self, query_id, rows):
                self.results[query_id] = rows
        """
        assert _rules(src, threaded=True) == ["W015"]

    def test_flags_setdefault_keyed_by_request_value(self):
        src = """
        class Server:
            def __init__(self):
                self.inflight = dict()

            def do_POST(self, qid, fut):
                self.inflight.setdefault(qid, fut)
        """
        assert _rules(src, threaded=True) == ["W015"]

    def test_quiet_on_bounded_deque(self):
        src = """
        from collections import deque

        class Broker:
            def __init__(self):
                self.audit = deque(maxlen=128)

            def execute(self, ctx):
                self.audit.append(ctx.sql)
        """
        assert _rules(src, threaded=True) == []

    def test_quiet_with_eviction_evidence(self):
        src = """
        class Server:
            def __init__(self):
                self.inflight = {}

            def handle(self, query_id, fut):
                self.inflight[query_id] = fut

            def finish(self, query_id):
                self.inflight.pop(query_id, None)
        """
        assert _rules(src, threaded=True) == []

    def test_quiet_when_reassigned_outside_init(self):
        src = """
        class Broker:
            def __init__(self):
                self.batch = []

            def execute(self, ctx):
                self.batch.append(ctx.sql)

            def flush(self):
                self.batch = []
        """
        assert _rules(src, threaded=True) == []

    def test_quiet_on_bounded_label_key_and_setup_methods(self):
        src = """
        class Coordinator:
            def __init__(self):
                self.tables = {}
                self.listeners = []

            def handle(self, table, meta):
                self.tables[table] = meta  # bounded label space

            def register(self, cb):
                self.listeners.append(cb)  # setup, not serving
        """
        assert _rules(src, threaded=True) == []

    def test_rule_is_threaded_scope_only(self):
        src = """
        class Recorder:
            def __init__(self):
                self.rows = []

            def record(self, row):
                self.rows.append(row)
        """
        assert _rules(src, threaded=False) == []
        assert _rules(src, threaded=True) == ["W015"]


class TestW016DurableWriteDiscipline:
    def test_flags_in_place_write_to_checkpoint_path(self):
        src = """
        import json

        def save(state, path):
            with open(path + "/checkpoint.json", "w") as f:
                json.dump(state, f)
        """
        assert _rules(src) == ["W016"]

    def test_flags_bare_write_in_commit_function(self):
        src = """
        import json

        def commit_state(state, path):
            with open(path, "w") as f:
                json.dump(state, f)
        """
        assert _rules(src) == ["W016"]

    def test_flags_binary_manifest_write(self):
        src = """
        def dump(blob, d):
            with open(d + "/manifest.bin", "wb") as f:
                f.write(blob)
        """
        assert _rules(src) == ["W016"]

    def test_quiet_with_tmp_fsync_replace_discipline(self):
        src = """
        import json, os

        def commit_checkpoint(state, path):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(state, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        """
        assert _rules(src) == []

    def test_quiet_with_durable_write_helper(self):
        src = """
        from pinot_tpu.spi.filesystem import durable_write_json

        def commit_checkpoint(state, path):
            durable_write_json(path, state)
        """
        assert _rules(src) == []

    def test_quiet_on_non_durable_paths_and_reads(self):
        src = """
        import json

        def export_report(rows, path):
            with open(path + "/report.csv", "w") as f:
                f.write(rows)

        def load_checkpoint(path):
            with open(path + "/checkpoint.json") as f:
                return json.load(f)
        """
        assert _rules(src) == []

    def test_runs_unthreaded_everywhere(self):
        src = """
        def write_journal(entries, path):
            with open(path, "w") as f:
                f.writelines(entries)
        """
        assert _rules(src, threaded=False) == ["W016"]
        assert _rules(src, threaded=True) == ["W016"]


class TestW017UnfencedDispatchTiming:
    def test_flags_perf_counter_around_jitted_name_call(self):
        src = """
        import time
        import jax

        def kernel(x):
            return x + x

        kernel_jit = jax.jit(kernel)

        def bench(x):
            t0 = time.perf_counter()
            y = kernel_jit(x)
            dt = time.perf_counter() - t0
            return y, dt
        """
        assert _rules(src) == ["W017"]

    def test_flags_monotonic_around_decorated_jit(self):
        src = """
        import time
        import jax

        @jax.jit
        def kernel(x):
            return x + x

        def bench(x):
            t0 = time.monotonic()
            y = kernel(x)
            return time.monotonic() - t0
        """
        assert _rules(src) == ["W017"]

    def test_quiet_with_fence_before_stop(self):
        src = """
        import time
        import jax

        def kernel(x):
            return x + x

        kernel_jit = jax.jit(kernel)

        def bench(x):
            t0 = time.perf_counter()
            y = kernel_jit(x)
            y.block_until_ready()
            dt = time.perf_counter() - t0
            return y, dt
        """
        assert _rules(src) == []

    def test_quiet_with_fence_wrapping_dispatch(self):
        src = """
        import time
        import jax

        def kernel(x):
            return x + x

        kernel_jit = jax.jit(kernel)

        def bench(x):
            t0 = time.perf_counter()
            y = jax.device_get(kernel_jit(x))
            dt = time.perf_counter() - t0
            return y, dt
        """
        assert _rules(src) == []

    def test_quiet_on_attribute_call_dispatch(self):
        # timing plan.fn(...) is the engine's compile_ms capture — the
        # dispatch cost IS the measurement there, so attr calls are out of
        # scope by design
        src = """
        import time
        import jax

        def kernel(x):
            return x + x

        kernel_jit = jax.jit(kernel)

        def launch(plan, x):
            t0 = time.perf_counter()
            y = plan.fn(x)
            dt = time.perf_counter() - t0
            return y, dt
        """
        assert _rules(src) == []

    def test_quiet_without_timer_or_without_dispatch(self):
        src = """
        import time
        import jax

        def kernel(x):
            return x + x

        kernel_jit = jax.jit(kernel)

        def run(x):
            return kernel_jit(x)

        def host_only():
            t0 = time.perf_counter()
            total = sum(range(100))
            return time.perf_counter() - t0, total
        """
        assert _rules(src) == []


class TestW018BlockingInDispatch:
    def test_flags_sleep_in_batcher_pump(self):
        src = """
        import time

        class MicroBatcher:
            def pump(self, now=None):
                time.sleep(0.001)  # busy-wait for stragglers
                return self._flush(now)
        """
        assert _rules(src, threaded=True) == ["W018"]

    def test_flags_device_fence_in_dispatch_loop(self):
        src = """
        def broker_dispatch_loop(queue):
            out = queue.popleft()
            out.block_until_ready()
        """
        assert _rules(src, threaded=True) == ["W018"]

    def test_flags_socket_wait_in_batcher_method(self):
        src = """
        class QueryBatcher:
            def drain(self, sock):
                return sock.recv(4096)
        """
        assert _rules(src, threaded=True) == ["W018"]

    def test_quiet_on_condition_wait_and_out_of_scope_sleep(self):
        src = """
        import time

        class MicroBatcher:
            def pump(self, now=None):
                with self._cv:
                    self._cv.wait(timeout=0.01)  # sanctioned wakeup
                return 0

        def warmup():
            time.sleep(0.5)  # not a dispatch path
        """
        assert _rules(src, threaded=True) == []

    def test_rule_is_threaded_scope_only(self):
        src = """
        import time

        class MicroBatcher:
            def pump(self):
                time.sleep(0.001)
        """
        assert _rules(src, threaded=False) == []
        assert _rules(src, threaded=True) == ["W018"]


class TestW019RetryLoopDiscipline:
    def test_flags_retry_loop_without_backoff(self):
        src = """
        def scatter(server, ctx, segs, cancel):
            while segs:
                res = server.execute(ctx, segs, cancel=cancel)
                segs = res.failed
        """
        assert _rules(src, threaded=True) == ["W019"]

    def test_flags_reissue_without_cancel_probe(self):
        src = """
        import time

        def scatter(server, ctx, segs):
            while segs:
                res = server.execute(ctx, segs)
                segs = res.failed
                time.sleep(0.002)
        """
        assert _rules(src, threaded=True) == ["W019"]

    def test_flags_batch_reissue_without_cancels(self):
        src = """
        def rebatch(server, ctxs, segs, sleep):
            while segs:
                out = server.execute_batch(ctxs, segs)
                segs = out.failed
                sleep(0.002)
        """
        assert _rules(src, threaded=True) == ["W019"]

    def test_quiet_on_backoff_plus_cancel(self):
        src = """
        def scatter(self, server, ctx, segs, cancel):
            while segs:
                res = server.execute(ctx, segs, cancel=cancel)
                segs = res.failed
                self._sleep(0.002)
        """
        assert _rules(src, threaded=True) == []

    def test_quiet_on_fan_out_for_loop(self):
        src = """
        def fan_out(servers, ctx):
            out = []
            for server in servers:
                out.append(server.execute(ctx, ["seg"]))
            return out
        """
        assert _rules(src, threaded=True) == []

    def test_quiet_on_nested_cancel_closure(self):
        src = """
        def scatter(self, server, ctx, segs, cancel):
            while segs:
                def run_one(name, _segs=segs):
                    return server.execute(ctx, _segs, cancel=cancel)
                segs = self._hedged(run_one)
                self._sleep(0.001)
        """
        assert _rules(src, threaded=True) == []

    def test_rule_is_threaded_scope_only(self):
        src = """
        def scatter(server, ctx, segs):
            while segs:
                segs = server.execute(ctx, segs).failed
        """
        assert _rules(src, threaded=False) == []
        assert sorted(set(_rules(src, threaded=True))) == ["W019"]


class TestW020PackedWidenBeforeUnpack:
    def test_flags_astype_on_packed_words_without_shift(self):
        src = """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def scan_kernel(words_ref, o_ref):
            packed = words_ref[...]
            wide = packed.astype(jnp.int32)  # widens BEFORE the lane unpack
            o_ref[...] = wide & 0xF

        def run(x):
            return pl.pallas_call(scan_kernel, out_shape=x)(x)
        """
        assert _rules(src) == ["W020"]

    def test_flags_ref_read_named_words(self):
        src = """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def scan_kernel(refs, o_ref):
            key_words = refs[...]
            o_ref[...] = key_words.astype(jnp.float32)

        def run(x):
            return pl.pallas_call(scan_kernel, out_shape=x)(x)
        """
        assert _rules(src) == ["W020"]

    def test_quiet_when_shift_precedes_cast(self):
        src = """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def scan_kernel(words_ref, o_ref):
            packed = words_ref[...]
            lanes = (packed[:, None] >> jnp.uint32(4)) & jnp.uint32(0xF)
            o_ref[...] = lanes.astype(jnp.int32)  # cast AFTER the unpack

        def run(x):
            return pl.pallas_call(scan_kernel, out_shape=x)(x)
        """
        assert _rules(src) == []

    def test_quiet_on_unpacked_operand_cast(self):
        src = """
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def scan_kernel(key_ref, o_ref):
            o_ref[...] = key_ref[...].astype(jnp.int32)  # plain codes, not packed

        def run(x):
            return pl.pallas_call(scan_kernel, out_shape=x)(x)
        """
        assert _rules(src) == []

    def test_rule_scope_is_pallas_kernels_only(self):
        src = """
        import jax
        import jax.numpy as jnp

        def host_helper(packed_words):
            return packed_words.astype(jnp.int64)  # jit kernel, not Pallas

        fn = jax.jit(host_helper)
        """
        assert _rules(src) == []


class TestW021UnbudgetedSegmentDevicePut:
    def test_flags_bare_device_put_of_segment_codes(self):
        src = """
        import jax

        def serve(segment_codes, device):
            return jax.device_put(segment_codes, device)
        """
        assert _rules(src) == ["W021"]

    def test_flags_attribute_operand(self):
        src = """
        import jax

        def pin(self, device):
            return jax.device_put(self.values, device)
        """
        assert _rules(src) == ["W021"]

    def test_quiet_inside_staging_scopes(self):
        src = """
        import jax

        def to_device(self, device):
            return jax.device_put(self.codes, device)

        def _stage_entry(plan_packed, device):
            return jax.device_put(plan_packed, device)
        """
        assert _rules(src) == []

    def test_quiet_on_small_per_query_params(self):
        src = """
        import jax

        def dispatch(v, params, device):
            a = jax.device_put(v, device)
            b = jax.device_put(params, device)
            return a, b
        """
        assert _rules(src) == []

    def test_nested_non_staging_helper_is_not_exempt(self):
        src = """
        import jax

        def to_device(self, device):
            def pin_all(column_arrays):
                return jax.device_put(column_arrays, device)
            return pin_all(self.columns)
        """
        assert _rules(src) == ["W021"]


class TestW022WallClockInLeaseCode:
    def test_flags_deadline_addition_in_lease_class(self):
        # the exact bug W005 misses: lease deadline built by ADDITION
        src = """
        import time

        class LeaseManager:
            def acquire(self, ttl_s):
                return time.time() + ttl_s
        """
        assert _rules(src) == ["W022"]

    def test_flags_alias_compare_in_election_function(self):
        src = """
        import time

        def run_election_tick(lease):
            now = time.time()
            return lease.expires_at <= now
        """
        # W005 also fires on the comparison; W022 must be among the findings
        assert "W022" in _rules(src)

    def test_flags_epoch_identifier_mix_outside_scoped_names(self):
        src = """
        import time

        def check_fresh(entry_epoch, ttl_s):
            return entry_epoch > time.time() - ttl_s
        """
        assert "W022" in _rules(src)

    def test_quiet_on_injectable_clock_in_lease_code(self):
        src = """
        class LeaseManager:
            def acquire(self, ttl_s):
                deadline = self.clock() + ttl_s
                return deadline

            def expired(self, lease):
                return lease.expires_at <= self.now()
        """
        assert _rules(src) == []

    def test_quiet_on_epoch_timestamp_stamping_and_retention_math(self):
        # epoch-millis stamping is multiplication; retention math never
        # mixes time.time() into the same expression — both clean
        src = """
        import time

        def seal(segment):
            segment.creationTimeMs = int(time.time() * 1000)

        def run_retention(self, now_ms, retention_ms):
            horizon = now_ms - retention_ms
            return [s for s in self.segments if s.end_ms < horizon]
        """
        assert _rules(src) == []


class TestW025BareAxisLiteralInCollective:
    def test_flags_string_literal_axis_in_psum(self):
        src = """
        from jax import lax

        def combine(x):
            return lax.psum(x, "seg")
        """
        assert _rules(src) == ["W025"]

    def test_flags_tuple_literal_axes_in_all_gather(self):
        src = """
        from jax import lax

        def fetch(v):
            return lax.all_gather(v, ("replica", "shard"), tiled=True)
        """
        assert _rules(src) == ["W025"]

    def test_flags_axis_name_keyword_on_jax_lax_call(self):
        src = """
        import jax

        def exchange(buf):
            return jax.lax.all_to_all(
                buf, axis_name="shard", split_axis=0, concat_axis=0
            )
        """
        assert _rules(src) == ["W025"]

    def test_flags_axis_index_literal(self):
        src = """
        from jax import lax

        def my_device():
            return lax.axis_index("replica")
        """
        assert _rules(src) == ["W025"]

    def test_quiet_on_threaded_axis_variable(self):
        src = """
        from jax import lax

        def combine(x, axis):
            return lax.psum(x, axis)
        """
        assert _rules(src) == []

    def test_quiet_on_mesh_module_constants(self):
        src = """
        from jax import lax
        from pinot_tpu.parallel import mesh as mesh_mod

        def combine(x):
            return lax.psum(x, mesh_mod.SEG_AXIS)
        """
        assert _rules(src) == []

    def test_quiet_on_non_axis_string_and_non_collective_calls(self):
        # a cache-group key tuple containing "seg" is NOT a collective arg
        # (segment/segment.py keys caches this way) and psum on some other
        # object is not a mesh collective
        src = """
        def key_for(self, device):
            return ("seg", id(self), device)

        def reduce_with(engine, x):
            return engine.psum(x, "seg")
        """
        assert _rules(src) == []

    def test_exempt_inside_parallel_mesh(self):
        src = """
        from jax import lax

        def psum_hierarchical(x):
            return lax.psum(x, "shard")
        """
        out = lint_source(textwrap.dedent(src), path="pinot_tpu/parallel/mesh.py")
        assert out == []


class TestW026ControllerDiscipline:
    def test_flags_direct_knob_write_outside_setter(self):
        # runtime knob mutation skipping the clamped registry setter
        src = """
        class Adaptor:
            def react(self, hc):
                hc.budget_pct = 60.0
        """
        assert _rules(src) == ["W026"]

    def test_flags_augassign_on_managed_knob(self):
        src = """
        def widen(batcher):
            batcher.wait_ms += 1.0
        """
        assert _rules(src) == ["W026"]

    def test_flags_wall_clock_inside_autopilot_module(self):
        src = """
        import time

        class Autopilot:
            def tick(self):
                return time.monotonic()
        """
        out = lint_source(textwrap.dedent(src), path="cluster/autopilot.py")
        assert [f.rule for f in out] == ["W026"]

    def test_quiet_on_init_wiring_and_property_setter(self):
        # construction wires defaults; the property setter IS the sanctioned
        # pin-the-override path (stores an underscore override)
        src = """
        class MicroBatcher:
            def __init__(self, wait_ms):
                self.wait_ms = wait_ms

            @wait_ms.setter
            def wait_ms(self, value):
                self._wait_ms_override = float(value)
        """
        assert _rules(src) == []

    def test_quiet_on_injected_clock_in_autopilot_module(self):
        # threads.monotonic is the injection seam, self.clock() the fake —
        # neither is the wall clock
        src = """
        from pinot_tpu.utils import threads

        class Autopilot:
            def tick(self):
                return self.clock() + threads.monotonic()
        """
        out = lint_source(textwrap.dedent(src), path="cluster/autopilot.py")
        assert out == []

    def test_quiet_on_wall_clock_outside_autopilot_module(self):
        # the wall-clock half of W026 is scoped to autopilot modules (other
        # wall-clock misuse belongs to W005/W017/W022)
        src = """
        import time

        def stamp():
            return time.monotonic()
        """
        assert _rules(src) == []


def test_syntax_error_is_a_finding_not_a_crash():
    out = lint_source("def broken(:\n", path="x.py")
    assert len(out) == 1 and out[0].rule == "E000"


def test_finding_str_is_greppable():
    f = Finding("a/b.py", 12, "W001", "msg")
    assert str(f) == "a/b.py:12: W001 msg"


def test_live_tree_is_clean():
    """The shipped package must lint clean — this is the CI gate that keeps
    the broker-race class of bug from regressing."""
    findings = lint_tree()
    assert findings == [], "\n".join(str(f) for f in findings)
