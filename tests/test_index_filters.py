"""Round-2: index-accelerated filtering (BitmapBasedFilterOperator /
SortedIndexBasedFilterOperator analogs).  Every query runs against two
identical tables — one fully indexed, one bare — and must return identical
rows; the indexed plan must report index use and must NOT ship the
filter-only column to the device."""
import numpy as np
import pytest

from pinot_tpu.query import planner
from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.sql.parser import parse_query

N = 6000
CITIES = ["sf", "nyc", "chi", "la", "sea", "pdx", "atx"]


def _schema(name):
    return Schema(
        name,
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("year", DataType.INT),
            FieldSpec("day", DataType.INT),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(21)
    data = {
        "city": rng.choice(CITIES, N).astype(object),
        "year": rng.integers(2000, 2020, N).astype(np.int32),
        "day": rng.integers(0, 366, N).astype(np.int32),
        "v": rng.integers(0, 100_000, N),
    }
    engine = QueryEngine()

    plain_schema = _schema("plain")
    engine.register_table(plain_schema, TableConfig("plain"))
    engine.add_segment("plain", build_segment(plain_schema, dict(data), "p0"))

    idx_schema = _schema("indexed")
    cfg = TableConfig(
        "indexed",
        indexing=IndexingConfig(
            inverted_index_columns=["city"],
            range_index_columns=["year"],
            sorted_column="day",
        ),
    )
    engine.register_table(idx_schema, cfg)
    engine.add_segment("indexed", build_segment(idx_schema, dict(data), "i0", table_config=cfg))
    return engine


QUERIES = [
    ("SELECT COUNT(*), SUM(v) FROM {t} WHERE city = 'sf'", ("city", "inverted")),
    ("SELECT COUNT(*), SUM(v) FROM {t} WHERE city IN ('sf', 'nyc', 'la')", ("city", "inverted")),
    ("SELECT COUNT(*), SUM(v) FROM {t} WHERE city != 'chi'", ("city", "inverted")),
    ("SELECT COUNT(*), SUM(v) FROM {t} WHERE year > 2010", ("year", "range")),
    ("SELECT COUNT(*), SUM(v) FROM {t} WHERE year BETWEEN 2005 AND 2012", ("year", "range")),
    ("SELECT COUNT(*), SUM(v) FROM {t} WHERE day < 100", ("day", "sorted")),
    ("SELECT COUNT(*), SUM(v) FROM {t} WHERE day = 250", ("day", "sorted")),
    (
        "SELECT year, COUNT(*) FROM {t} WHERE city = 'sf' AND day >= 180 "
        "GROUP BY year ORDER BY year LIMIT 25",
        ("city", "inverted"),
    ),
    ("SELECT city FROM {t} WHERE year = 2001 AND day > 350 ORDER BY city LIMIT 5", ("year", "range")),
]


@pytest.mark.parametrize("sql_tpl,expected_use", QUERIES)
def test_indexed_matches_scan(env, sql_tpl, expected_use):
    got_plain = env.query(sql_tpl.format(t="plain"))
    got_idx = env.query(sql_tpl.format(t="indexed"))
    assert got_idx.rows == got_plain.rows
    assert expected_use in got_idx.stats.filter_index_uses
    assert not got_plain.stats.filter_index_uses


def test_indexed_filter_column_not_shipped(env):
    """An EQ predicate answered by the inverted index must not load the
    filter column's codes onto the device at all."""
    ctx = parse_query("SELECT SUM(v) FROM indexed WHERE city = 'sf'")
    seg = env.tables["indexed"].segments[0]
    plan = planner.plan_segment(ctx, seg)
    assert ("city", "inverted") in plan.index_uses
    assert "city" not in plan.needed_columns
    assert "v" in plan.needed_columns
    # bitmap words param shipped instead: ceil(N/32) uint32 words
    bits_params = [v for k, v in plan.params.items() if k.endswith(".bits")]
    assert len(bits_params) == 1 and bits_params[0].dtype == np.uint32
    assert bits_params[0].shape[0] == -(-N // 32)


def test_sorted_range_zero_reads(env):
    """A sorted-column range predicate compiles to two int params (doc
    range) — no column data and no bitmap shipped."""
    ctx = parse_query("SELECT COUNT(*) FROM indexed WHERE day < 50")
    seg = env.tables["indexed"].segments[0]
    plan = planner.plan_segment(ctx, seg)
    assert ("day", "sorted") in plan.index_uses
    assert "day" not in plan.needed_columns
    assert all(np.asarray(v).size <= 1 for v in plan.params.values())


def test_index_nulls_respected():
    """3VL: index-resolved predicates still exclude NULL rows."""
    schema = Schema(
        "nt",
        [
            FieldSpec("c", DataType.STRING, nullable=True),
            FieldSpec("v", DataType.INT, role=FieldRole.METRIC),
        ],
    )
    cfg = TableConfig("nt", indexing=IndexingConfig(inverted_index_columns=["c"]))
    e = QueryEngine()
    e.register_table(schema, cfg)
    data = {
        "c": np.array(["a", None, "b", "a", None, "b", "a"], dtype=object),
        "v": np.arange(7, dtype=np.int32),
    }
    e.add_segment("nt", build_segment(schema, data, "n0", table_config=cfg))
    r = e.query("SELECT COUNT(*) FROM nt WHERE c != 'a'")
    assert r.rows[0][0] == 2  # b rows only; NULLs excluded by 3VL
    assert ("c", "inverted") in r.stats.filter_index_uses


# ---------------------------------------------------------------------------
# Distributed path (round 3): StackedTable carries indexes; the shard_map
# kernels ride shard-sliced bitmap words / global doc ranges instead of
# code scans, and index-only columns never ship to device.
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def dist_env():
    from pinot_tpu.parallel.engine import DistributedEngine
    from pinot_tpu.parallel.stacked import StackedTable

    rng = np.random.default_rng(22)
    data = {
        "city": rng.choice(CITIES, N).astype(object),
        "year": rng.integers(2000, 2020, N).astype(np.int32),
        "day": np.sort(rng.integers(0, 366, N).astype(np.int32)),
        "v": rng.integers(0, 100_000, N),
    }
    cfg = TableConfig(
        "indexed",
        indexing=IndexingConfig(
            inverted_index_columns=["city"],
            range_index_columns=["year"],
            sorted_column="day",
        ),
    )
    eng = DistributedEngine()
    eng.register_table(
        "indexed",
        StackedTable.build(_schema("indexed"), dict(data), eng.num_devices, table_config=cfg),
    )
    eng.register_table("plain", StackedTable.build(_schema("plain"), dict(data), eng.num_devices))
    return eng


@pytest.mark.parametrize("sql_tpl,expected_use", QUERIES)
def test_distributed_indexed_matches_scan(dist_env, sql_tpl, expected_use):
    got_plain = dist_env.query(sql_tpl.format(t="plain"))
    got_idx = dist_env.query(sql_tpl.format(t="indexed"))
    assert got_idx.rows == got_plain.rows
    assert expected_use in got_idx.stats.filter_index_uses
    # the plain table has no configured indexes, but its physically-sorted
    # `day` column still legitimately takes the sorted doc-range path
    assert all(kind == "sorted" for _, kind in got_plain.stats.filter_index_uses)


def test_distributed_bitmap_params_shard_sliced(dist_env):
    """The distributed EQ plan ships [ndev, words] bitmap slices, not codes."""
    ctx = parse_query("SELECT SUM(v) FROM indexed WHERE city = 'sf'")
    stacked = dist_env.tables["indexed"]
    plan = dist_env._plan(ctx, stacked)
    assert ("city", "inverted") in plan.index_uses
    assert "city" not in plan.needed_columns
    bits = [plan.params[k] for k in plan.row_sharded_params]
    assert len(bits) == 1
    ndev = dist_env.num_devices
    L = stacked.num_shards // ndev
    # stored full as [ndev, L, D//32]; launch params slice the doc axis
    assert bits[0].shape == (ndev, L, stacked.docs_per_shard // 32)
    key = next(iter(plan.row_sharded_params))
    launch = dist_env.batch_params(plan, 0, 0)
    assert launch[key].shape == (ndev, L * plan.batch_docs // 32)


def test_distributed_sorted_doc_range(dist_env):
    """Sorted-column predicates over the stacked table: global doc-range
    params, no bitmap, no column shipment."""
    ctx = parse_query("SELECT COUNT(*) FROM indexed WHERE day < 50")
    stacked = dist_env.tables["indexed"]
    plan = dist_env._plan(ctx, stacked)
    assert ("day", "sorted") in plan.index_uses
    assert "day" not in plan.needed_columns
    assert not plan.row_sharded_params
    assert all(np.asarray(v).size <= 1 for v in plan.params.values())


def test_mse_join_with_indexed_fact_filter(dist_env):
    """Join queries pick up fact-side index acceleration too."""
    from pinot_tpu.parallel.stacked import StackedTable as _ST

    dim = {
        "y": np.arange(2000, 2020, dtype=np.int32),
        "decade": (np.arange(2000, 2020) // 10).astype(np.int32),
    }
    dschema = Schema("years", [FieldSpec("y", DataType.INT), FieldSpec("decade", DataType.INT)])
    dist_env.register_table("years", _ST.build(dschema, dim, dist_env.num_devices))
    res = dist_env.query(
        "SELECT decade, COUNT(*) FROM indexed JOIN years ON year = y "
        "WHERE city = 'sf' GROUP BY decade ORDER BY decade LIMIT 10"
    )
    assert ("city", "inverted") in res.stats.filter_index_uses
    plain = dist_env.query(
        "SELECT city, COUNT(*) FROM indexed WHERE city = 'sf' GROUP BY city"
    )
    assert sum(int(r[1]) for r in res.rows) == int(plain.rows[0][1])
