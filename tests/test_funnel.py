"""Funnel aggregation family (VERDICT r4 missing #3 tail).

Reference model: pinot-core/.../query/aggregation/function/funnel/
FunnelCountAggregationFunction.java (bitmap set-intersection strategy),
FunnelCompleteCount / FunnelMaxStep siblings.  Golden model: python sets.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

N = 30_000


@pytest.fixture(scope="module")
def funnel_world():
    rng = np.random.default_rng(71)
    uid = rng.integers(0, 800, N).astype(np.int64)
    url = rng.choice(["/home", "/product", "/cart", "/checkout"], N, p=[0.5, 0.3, 0.15, 0.05])
    country = rng.choice(["us", "de"], N)
    schema = Schema(
        "events",
        [
            FieldSpec("uid", DataType.LONG),
            FieldSpec("url", DataType.STRING),
            FieldSpec("country", DataType.STRING),
        ],
    )
    eng = QueryEngine()
    eng.register_table(schema)
    # 3 segments to exercise the presence-bitmap merge
    bounds = np.linspace(0, N, 4).astype(int)
    for i in range(3):
        chunk = {
            "uid": uid[bounds[i] : bounds[i + 1]],
            "url": url[bounds[i] : bounds[i + 1]],
            "country": country[bounds[i] : bounds[i + 1]],
        }
        eng.add_segment("events", build_segment(schema, chunk, f"s{i}"))
    return eng, uid, url, country


def _step_sets(uid, url, conds):
    return [set(uid[url == c]) for c in conds]


CONDS = ["/home", "/product", "/cart", "/checkout"]


class TestFunnelCount:
    def test_counts_per_step(self, funnel_world):
        eng, uid, url, _ = funnel_world
        got = eng.query(
            "SELECT FUNNELCOUNT(STEPS(url = '/home', url = '/product', url = '/cart', "
            "url = '/checkout'), CORRELATEBY(uid)) FROM events"
        ).rows[0][0]
        sets = _step_sets(uid, url, CONDS)
        want = []
        acc = None
        for s in sets:
            acc = s if acc is None else (acc & s)
            want.append(len(acc))
        assert got == want

    def test_filtered(self, funnel_world):
        eng, uid, url, country = funnel_world
        got = eng.query(
            "SELECT FUNNELCOUNT(STEPS(url = '/home', url = '/cart'), CORRELATEBY(uid)) "
            "FROM events WHERE country = 'us'"
        ).rows[0][0]
        sel = country == "us"
        sets = _step_sets(uid[sel], url[sel], ["/home", "/cart"])
        assert got == [len(sets[0]), len(sets[0] & sets[1])]

    def test_complete_and_maxstep(self, funnel_world):
        eng, uid, url, _ = funnel_world
        row = eng.query(
            "SELECT FUNNELCOMPLETECOUNT(STEPS(url = '/home', url = '/product', url = '/cart', "
            "url = '/checkout'), CORRELATEBY(uid)), "
            "FUNNELMAXSTEP(STEPS(url = '/home', url = '/product', url = '/cart', "
            "url = '/checkout'), CORRELATEBY(uid)) FROM events"
        ).rows[0]
        sets = _step_sets(uid, url, CONDS)
        complete = sets[0] & sets[1] & sets[2] & sets[3]
        assert int(row[0]) == len(complete)
        # maxstep: deepest prefix any uid completes
        best = 0
        acc = None
        for i, s in enumerate(sets):
            acc = s if acc is None else (acc & s)
            if acc:
                best = i + 1
        assert int(row[1]) == best

    def test_grouped_funnel(self, funnel_world):
        eng, uid, url, country = funnel_world
        res = eng.query(
            "SELECT country, FUNNELCOUNT(STEPS(url = '/home', url = '/product'), "
            "CORRELATEBY(uid)) FROM events GROUP BY country ORDER BY country"
        )
        for c, counts in res.rows:
            sel = country == c
            sets = _step_sets(uid[sel], url[sel], ["/home", "/product"])
            assert counts == [len(sets[0]), len(sets[0] & sets[1])], c

    def test_complex_step_conditions(self, funnel_world):
        eng, uid, url, country = funnel_world
        got = eng.query(
            "SELECT FUNNELCOUNT(STEPS(url = '/home' AND country = 'us', "
            "url IN ('/cart', '/checkout')), CORRELATEBY(uid)) FROM events"
        ).rows[0][0]
        s1 = set(uid[(url == "/home") & (country == "us")])
        s2 = set(uid[np.isin(url, ["/cart", "/checkout"])])
        assert got == [len(s1), len(s1 & s2)]


def test_underscore_aliases(funnel_world):
    eng, uid, url, _ = funnel_world
    got = eng.query(
        "SELECT FUNNEL_COUNT(STEPS(url = '/home', url = '/cart'), CORRELATEBY(uid)) FROM events"
    ).rows[0][0]
    sets = _step_sets(uid, url, ["/home", "/cart"])
    assert got == [len(sets[0]), len(sets[0] & sets[1])]
