"""Statistics long-tail aggregations (VERDICT r4 #8): HISTOGRAM, covariance
family, EXPR_MIN/EXPR_MAX, FREQUENTSTRINGS, integer tuple sketches.

Reference model: HistogramAggregationFunction (bin semantics: [e, e') bins,
last bin closed, out-of-range dropped), CovarianceAggregationFunction
(CovarianceTuple merge), ParentExprMinMaxAggregationFunction,
FrequentStringsSketchAggregationFunction, IntegerTupleSketchAggregationFunction.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

N = 40_000


def _make_engine(data, schema, n_segments=3):
    eng = QueryEngine()
    eng.register_table(schema)
    n = len(next(iter(data.values())))
    bounds = np.linspace(0, n, n_segments + 1).astype(int)
    for i in range(n_segments):
        chunk = {k: v[bounds[i] : bounds[i + 1]] for k, v in data.items()}
        eng.add_segment(schema.name, build_segment(schema, chunk, f"s{i}"))
    return eng


@pytest.fixture(scope="module")
def xy_engine():
    rng = np.random.default_rng(23)
    g = rng.integers(0, 4, N).astype(np.int32)
    x = rng.normal(0, 10, N)
    y = 3.0 * x + rng.normal(0, 5, N) + g
    m = rng.integers(0, 1_000_000, N).astype(np.int64)
    schema = Schema(
        "xy",
        [
            FieldSpec("g", DataType.INT),
            FieldSpec("x", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("y", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("m", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    data = {"g": g, "x": x, "y": y, "m": m}
    return _make_engine(data, schema), data


class TestHistogram:
    def test_equal_width(self, xy_engine):
        eng, data = xy_engine
        res = eng.query("SELECT HISTOGRAM(x, -30, 30, 6) FROM xy")
        got = np.asarray(res.rows[0][0], dtype=np.float64)
        edges = np.linspace(-30, 30, 7)
        x = data["x"]
        want = np.histogram(x[(x >= -30) & (x <= 30)], bins=edges)[0]
        assert got.shape == (6,)
        np.testing.assert_allclose(got, want)

    def test_explicit_edges_and_last_bin_closed(self):
        vals = np.asarray([0.0, 0.5, 1.0, 5.0, 9.0, 10.0, 11.0, -1.0])
        schema = Schema("h", [FieldSpec("v", DataType.DOUBLE, role=FieldRole.METRIC)])
        eng = _make_engine({"v": vals}, schema, n_segments=2)
        got = np.asarray(eng.query("SELECT HISTOGRAM(v, '0,1,10') FROM h").rows[0][0])
        # bins [0,1), [1,10]; 10.0 joins the last bin; 11.0 and -1.0 drop
        np.testing.assert_allclose(got, [2, 4])

    def test_grouped(self, xy_engine):
        eng, data = xy_engine
        res = eng.query("SELECT g, HISTOGRAM(x, -30, 30, 6) FROM xy GROUP BY g ORDER BY g")
        edges = np.linspace(-30, 30, 7)
        for row in res.rows:
            sel = data["g"] == int(row[0])
            x = data["x"][sel]
            want = np.histogram(x[(x >= -30) & (x <= 30)], bins=edges)[0]
            np.testing.assert_allclose(np.asarray(row[1], np.float64), want)


class TestCovariance:
    def test_covar_pop_samp_corr(self, xy_engine):
        eng, data = xy_engine
        x, y = data["x"], data["y"]
        res = eng.query("SELECT COVAR_POP(x, y), COVAR_SAMP(x, y), CORR(x, y) FROM xy")
        want_pop = np.cov(x, y, bias=True)[0, 1]
        want_samp = np.cov(x, y, bias=False)[0, 1]
        want_corr = np.corrcoef(x, y)[0, 1]
        np.testing.assert_allclose(float(res.rows[0][0]), want_pop, rtol=1e-9)
        np.testing.assert_allclose(float(res.rows[0][1]), want_samp, rtol=1e-9)
        np.testing.assert_allclose(float(res.rows[0][2]), want_corr, rtol=1e-9)

    def test_grouped_covariance(self, xy_engine):
        eng, data = xy_engine
        res = eng.query("SELECT g, COVAR_POP(x, y) FROM xy GROUP BY g ORDER BY g")
        for row in res.rows:
            sel = data["g"] == int(row[0])
            want = np.cov(data["x"][sel], data["y"][sel], bias=True)[0, 1]
            np.testing.assert_allclose(float(row[1]), want, rtol=1e-9)

    def test_filtered_covariance(self, xy_engine):
        eng, data = xy_engine
        res = eng.query("SELECT COVAR_POP(x, y) FROM xy WHERE g = 2")
        sel = data["g"] == 2
        want = np.cov(data["x"][sel], data["y"][sel], bias=True)[0, 1]
        np.testing.assert_allclose(float(res.rows[0][0]), want, rtol=1e-9)


class TestExprMinMax:
    def test_scalar(self, xy_engine):
        eng, data = xy_engine
        res = eng.query("SELECT EXPR_MAX(x, m), EXPR_MIN(x, m), ARG_MAX(x, m) FROM xy")
        want_max = data["x"][np.argmax(data["m"])]
        want_min = data["x"][np.argmin(data["m"])]
        # ties on m are possible with random int64s but vanishingly unlikely
        np.testing.assert_allclose(float(res.rows[0][0]), want_max)
        np.testing.assert_allclose(float(res.rows[0][1]), want_min)
        np.testing.assert_allclose(float(res.rows[0][2]), want_max)

    def test_grouped(self, xy_engine):
        eng, data = xy_engine
        res = eng.query("SELECT g, EXPR_MIN(y, m) FROM xy GROUP BY g ORDER BY g")
        for row in res.rows:
            sel = np.nonzero(data["g"] == int(row[0]))[0]
            want = data["y"][sel[np.argmin(data["m"][sel])]]
            np.testing.assert_allclose(float(row[1]), want)

    def test_empty_filter_is_null(self, xy_engine):
        eng, _ = xy_engine
        res = eng.query("SELECT EXPR_MAX(x, m) FROM xy WHERE g = 99")
        v = res.rows[0][0]
        assert v is None or (isinstance(v, float) and np.isnan(v))


class TestFrequentStrings:
    def test_top_k(self):
        rng = np.random.default_rng(3)
        # zipf-ish frequencies over 20 city names
        names = np.asarray([f"city{i:02d}" for i in range(20)])
        weights = 1.0 / np.arange(1, 21)
        weights /= weights.sum()
        vals = rng.choice(names, size=N, p=weights)
        schema = Schema("c", [FieldSpec("city", DataType.STRING)])
        eng = _make_engine({"city": vals}, schema)
        got = eng.query("SELECT FREQUENTSTRINGS(city, 5) FROM c").rows[0][0]
        uniq, counts = np.unique(vals, return_counts=True)
        want = list(uniq[np.argsort(-counts, kind="stable")][:5])
        assert got == [str(w) for w in want]

    def test_grouped(self):
        rng = np.random.default_rng(9)
        g = rng.integers(0, 3, 9000)
        # group i's most common value is f"v{i}"
        vals = np.asarray([f"v{x}" if rng.random() < 0.5 else f"v{rng.integers(0, 9)}" for x in g])
        schema = Schema("fs", [FieldSpec("g", DataType.INT), FieldSpec("v", DataType.STRING)])
        eng = _make_engine({"g": g, "v": vals}, schema)
        res = eng.query("SELECT g, FREQUENTSTRINGS(v, 1) FROM fs GROUP BY g ORDER BY g")
        for row in res.rows:
            sel = g == int(row[0])
            u, c = np.unique(vals[sel], return_counts=True)
            assert row[1] == [str(u[np.argmax(c)])]


class TestIntegerTupleSketch:
    def test_exact_below_k(self):
        rng = np.random.default_rng(17)
        keys = rng.integers(0, 1000, N).astype(np.int64)  # 1000 distinct < K
        pay = rng.integers(0, 100, N).astype(np.int64)
        schema = Schema(
            "ts",
            [
                FieldSpec("k", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("p", DataType.LONG, role=FieldRole.METRIC),
            ],
        )
        eng = _make_engine({"k": keys, "p": pay}, schema)
        row = eng.query(
            "SELECT DISTINCTCOUNTTUPLESKETCH(k, p), "
            "SUMVALUESINTEGERSUMTUPLESKETCH(k, p) FROM ts"
        ).rows[0]
        assert int(row[0]) == len(np.unique(keys))
        # below K the sketch holds every key: summary sum is exact
        np.testing.assert_allclose(float(row[1]), float(pay.sum()))

    def test_estimates_above_k(self):
        rng = np.random.default_rng(29)
        nd = 200_000
        keys = rng.integers(0, nd, N * 4).astype(np.int64)
        pay = np.ones(len(keys), dtype=np.int64)
        schema = Schema(
            "tb",
            [
                FieldSpec("k", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("p", DataType.LONG, role=FieldRole.METRIC),
            ],
        )
        eng = _make_engine({"k": keys, "p": pay}, schema)
        row = eng.query(
            "SELECT DISTINCTCOUNTTUPLESKETCH(k, p), "
            "SUMVALUESINTEGERSUMTUPLESKETCH(k, p) FROM tb"
        ).rows[0]
        true_d = len(np.unique(keys))
        assert abs(int(row[0]) - true_d) / true_d < 0.10
        # payload=1 everywhere: sum estimate ~ total row count
        assert abs(float(row[1]) - len(keys)) / len(keys) < 0.10

    def test_avg_value(self):
        rng = np.random.default_rng(41)
        keys = np.repeat(np.arange(500, dtype=np.int64), 20)
        pay = rng.integers(1, 10, len(keys)).astype(np.int64)
        schema = Schema(
            "ta",
            [
                FieldSpec("k", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("p", DataType.LONG, role=FieldRole.METRIC),
            ],
        )
        eng = _make_engine({"k": keys, "p": pay}, schema)
        got = float(eng.query("SELECT AVGVALUEINTEGERSUMTUPLESKETCH(k, p) FROM ta").rows[0][0])
        # exact below K: mean per-key payload sum
        want = float(pay.sum()) / 500
        np.testing.assert_allclose(got, want)

    def test_grouped_distinct(self):
        rng = np.random.default_rng(53)
        g = rng.integers(0, 3, 30_000).astype(np.int32)
        keys = rng.integers(0, 150, 30_000).astype(np.int64) + g * 1000
        pay = np.ones(30_000, dtype=np.int64)
        schema = Schema(
            "tg",
            [
                FieldSpec("g", DataType.INT),
                FieldSpec("k", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("p", DataType.LONG, role=FieldRole.METRIC),
            ],
        )
        eng = _make_engine({"g": g, "k": keys, "p": pay}, schema)
        res = eng.query("SELECT g, DISTINCTCOUNTTUPLESKETCH(k, p) FROM tg GROUP BY g ORDER BY g")
        for row in res.rows:
            true = len(np.unique(keys[g == int(row[0])]))
            assert int(row[1]) == true  # 150 distinct < grouped K
