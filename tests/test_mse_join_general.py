"""MSE join generality (VERDICT r4 #7): join-output selection, snowflake
chains, M:N selection — vs sqlite on the 8-device CPU mesh.

Reference model: HashJoinOperator output rows + LookupJoinOperator dim->dim
chains (pinot-query-runtime/.../runtime/operator/HashJoinOperator.java,
LookupJoinOperator.java), golden-checked like Joins.json vs H2.
"""
import sqlite3

import numpy as np
import pytest

from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

N_FACT = 4000
N_DATE = 300
N_CITY = 24


@pytest.fixture(scope="module")
def world():
    rng = np.random.default_rng(77)
    citykeys = np.arange(N_CITY, dtype=np.int64) + 100
    regions = np.asarray([f"region{i % 5}" for i in range(N_CITY)])
    cities = {
        "c_citykey": citykeys,
        "c_region": regions,
        "c_pop": rng.integers(1, 1000, N_CITY).astype(np.int64),
    }
    city_schema = Schema(
        "city",
        [
            FieldSpec("c_citykey", DataType.INT),
            FieldSpec("c_region", DataType.STRING),
            FieldSpec("c_pop", DataType.LONG, role=FieldRole.METRIC),
        ],
    )

    datekeys = (19920101 + np.arange(N_DATE) * 7).astype(np.int64)
    dates = {
        "d_datekey": datekeys,
        "d_year": (1992 + (np.arange(N_DATE) // 53)).astype(np.int64),
        # every date belongs to a city -> snowflake chain fact->dates->city
        "d_citykey": rng.choice(citykeys, N_DATE).astype(np.int64),
    }
    date_schema = Schema(
        "dates",
        [
            FieldSpec("d_datekey", DataType.INT),
            FieldSpec("d_year", DataType.INT),
            FieldSpec("d_citykey", DataType.INT),
        ],
    )

    lineorder = {
        # ~10% of fact keys miss the date dim (inner drops / LEFT nulls)
        "lo_orderdate": rng.choice(
            np.concatenate([datekeys, datekeys[:1] - 99]), N_FACT
        ).astype(np.int64),
        "lo_revenue": rng.integers(1, 10_000, N_FACT).astype(np.int64),
        "lo_tag": rng.choice(["a", "b", "c"], N_FACT),
    }
    lo_schema = Schema(
        "lineorder",
        [
            FieldSpec("lo_orderdate", DataType.INT),
            FieldSpec("lo_revenue", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("lo_tag", DataType.STRING),
        ],
    )

    # M:N side: 3 shipments rows per datekey for the first 64 dates
    ship = {
        "s_datekey": np.repeat(datekeys[:64], 3).astype(np.int64),
        "s_mode": np.tile(np.asarray(["air", "sea", "rail"]), 64),
    }
    ship_schema = Schema(
        "ship",
        [FieldSpec("s_datekey", DataType.INT), FieldSpec("s_mode", DataType.STRING)],
    )

    eng = DistributedEngine()
    for name, schema, data in (
        ("lineorder", lo_schema, lineorder),
        ("dates", date_schema, dates),
        ("city", city_schema, cities),
        ("ship", ship_schema, ship),
    ):
        eng.register_table(name, StackedTable.build(schema, dict(data), eng.num_devices))

    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE lineorder (lo_orderdate, lo_revenue, lo_tag)")
    con.execute("CREATE TABLE dates (d_datekey, d_year, d_citykey)")
    con.execute("CREATE TABLE city (c_citykey, c_region, c_pop)")
    con.execute("CREATE TABLE ship (s_datekey, s_mode)")
    for t, cols, data in (
        ("lineorder", ("lo_orderdate", "lo_revenue", "lo_tag"), lineorder),
        ("dates", ("d_datekey", "d_year", "d_citykey"), dates),
        ("city", ("c_citykey", "c_region", "c_pop"), cities),
        ("ship", ("s_datekey", "s_mode"), ship),
    ):
        con.executemany(
            f"INSERT INTO {t} VALUES ({','.join('?' * len(cols))})",
            list(zip(*(np.asarray(data[c]).tolist() for c in cols))),
        )
    return eng, con


def norm(rows):
    out = []
    for r in rows:
        out.append(tuple(int(v) if isinstance(v, (np.integer,)) else v for v in r))
    return out


class TestJoinOutputSelection:
    def test_inner_selection_vs_sqlite(self, world):
        eng, con = world
        sql = (
            "SELECT d_year, lo_revenue FROM lineorder "
            "JOIN dates ON lo_orderdate = d_datekey "
            "WHERE lo_revenue > 9000 ORDER BY lo_revenue, d_year LIMIT 25"
        )
        got = norm(eng.query(sql).rows)
        want = con.execute(
            "SELECT d_year, lo_revenue FROM lineorder "
            "JOIN dates ON lo_orderdate = d_datekey "
            "WHERE lo_revenue > 9000 ORDER BY lo_revenue, d_year LIMIT 25"
        ).fetchall()
        assert got == norm(want)

    def test_left_join_selection_null_dims(self, world):
        eng, con = world
        sql = (
            "SELECT lo_orderdate, d_year FROM lineorder "
            "LEFT JOIN dates ON lo_orderdate = d_datekey "
            "ORDER BY lo_orderdate LIMIT 30"
        )
        got = eng.query(sql).rows
        want = con.execute(sql).fetchall()
        assert len(got) == len(want)
        for (a1, a2), (b1, b2) in zip(got, want):
            assert int(a1) == int(b1)
            assert (a2 is None and b2 is None) or int(a2) == int(b2)
        # unmatched keys exist and produce NULL d_year
        assert any(r[1] is None for r in got)

    def test_string_and_fact_columns(self, world):
        eng, con = world
        sql = (
            "SELECT lo_tag, d_year FROM lineorder "
            "JOIN dates ON lo_orderdate = d_datekey "
            "WHERE d_year = 1993 ORDER BY lo_tag, d_year LIMIT 20"
        )
        got = [(a, int(b)) for a, b in eng.query(sql).rows]
        want = con.execute(sql).fetchall()
        assert got == [(a, int(b)) for a, b in want]

    def test_mn_join_selection(self, world):
        eng, con = world
        sql = (
            "SELECT lo_revenue, s_mode FROM lineorder "
            "JOIN ship ON lo_orderdate = s_datekey "
            "WHERE lo_revenue > 9500 ORDER BY lo_revenue, s_mode LIMIT 30"
        )
        got = [(int(a), b) for a, b in eng.query(sql).rows]
        want = con.execute(sql).fetchall()
        assert got == [(int(a), b) for a, b in want]


class TestOrderPretrim:
    def test_numeric_looking_strings_sort_lexicographically(self):
        """Regression (review-caught): the ORDER BY pre-trim must rank
        numeric-LOOKING strings like the final Python comparator
        (lexicographic), not numerically."""
        import sqlite3 as sq

        eng = DistributedEngine()
        n = 64
        tags = np.asarray([str(v) for v in ([2, 9, 10, 100] * (n // 4))])
        keys = np.arange(n, dtype=np.int64) % 8
        eng.register_table(
            "f",
            StackedTable.build(
                Schema("f", [FieldSpec("f_tag", DataType.STRING), FieldSpec("f_k", DataType.INT)]),
                {"f_tag": tags, "f_k": keys},
                eng.num_devices,
            ),
        )
        eng.register_table(
            "d",
            StackedTable.build(
                Schema("d", [FieldSpec("d_k", DataType.INT), FieldSpec("d_v", DataType.INT)]),
                {"d_k": np.arange(8, dtype=np.int64), "d_v": np.arange(8, dtype=np.int64) * 2},
                eng.num_devices,
            ),
        )
        con = sq.connect(":memory:")
        con.execute("CREATE TABLE f (f_tag, f_k)")
        con.execute("CREATE TABLE d (d_k, d_v)")
        con.executemany("INSERT INTO f VALUES (?,?)", list(zip(tags.tolist(), keys.tolist())))
        con.executemany(
            "INSERT INTO d VALUES (?,?)", [(int(i), int(i) * 2) for i in range(8)]
        )
        sql = "SELECT f_tag, d_v FROM f JOIN d ON f_k = d_k ORDER BY f_tag, d_v LIMIT 5"
        got = [(a, int(b)) for a, b in eng.query(sql).rows]
        want = con.execute(sql).fetchall()
        assert got == [(a, int(b)) for a, b in want]
        assert got[0][0] == "10"  # lexicographic, not numeric


class TestSnowflake:
    def test_chain_groupby(self, world):
        eng, con = world
        sql = (
            "SELECT c_region, SUM(lo_revenue) FROM lineorder "
            "JOIN dates ON lo_orderdate = d_datekey "
            "JOIN city ON d_citykey = c_citykey "
            "GROUP BY c_region ORDER BY c_region"
        )
        got = [(a, int(b)) for a, b in eng.query(sql + " LIMIT 20").rows]
        want = [(a, int(b)) for a, b in con.execute(sql).fetchall()]
        assert got == want

    def test_chain_selection(self, world):
        eng, con = world
        sql = (
            "SELECT c_region, lo_revenue FROM lineorder "
            "JOIN dates ON lo_orderdate = d_datekey "
            "JOIN city ON d_citykey = c_citykey "
            "WHERE lo_revenue > 9200 ORDER BY lo_revenue, c_region LIMIT 25"
        )
        got = [(a, int(b)) for a, b in eng.query(sql).rows]
        want = [(a, int(b)) for a, b in con.execute(sql).fetchall()]
        assert got == want

    def test_chain_aggregation_count(self, world):
        eng, con = world
        sql = (
            "SELECT COUNT(*) FROM lineorder "
            "JOIN dates ON lo_orderdate = d_datekey "
            "JOIN city ON d_citykey = c_citykey "
            "WHERE c_pop > 500"
        )
        got = int(eng.query(sql).rows[0][0])
        want = con.execute(sql).fetchall()[0][0]
        assert got == want

    def test_chain_left_parent_semantics(self, world):
        eng, con = world
        # LEFT parent: unmatched dates rows must not match the chained city
        sql = (
            "SELECT lo_orderdate, c_region FROM lineorder "
            "LEFT JOIN dates ON lo_orderdate = d_datekey "
            "LEFT JOIN city ON d_citykey = c_citykey "
            "ORDER BY lo_orderdate LIMIT 30"
        )
        got = eng.query(sql).rows
        want = con.execute(sql).fetchall()
        assert len(got) == len(want)
        for (a1, a2), (b1, b2) in zip(got, want):
            assert int(a1) == int(b1)
            assert (a2 is None) == (b2 is None)
            if a2 is not None:
                assert a2 == b2

    def test_self_join_aggregation(self, world):
        eng, con = world
        # dates self-join: rows paired with the SAME-KEY row of another
        # instance (identity pairing exercises facade resolution end-to-end)
        sql = (
            "SELECT COUNT(*), SUM(lo_revenue) FROM lineorder "
            "JOIN dates d1 ON lo_orderdate = d1.d_datekey "
        )
        base = con.execute(
            "SELECT COUNT(*), SUM(lo_revenue) FROM lineorder "
            "JOIN dates d1 ON lo_orderdate = d1.d_datekey"
        ).fetchall()[0]
        got = eng.query(sql).rows[0]
        assert (int(got[0]), int(got[1])) == (int(base[0]), int(base[1]))

    def test_self_join_two_instances(self, world):
        eng, con = world
        sql = (
            "SELECT COUNT(*) FROM lineorder "
            "JOIN dates d1 ON lo_orderdate = d1.d_datekey "
            "JOIN dates d2 ON d1.d_datekey = d2.d_datekey "
            "WHERE d2.d_year = 1993"
        )
        got = int(eng.query(sql).rows[0][0])
        want = con.execute(sql).fetchall()[0][0]
        assert got == want

    def test_self_join_selection(self, world):
        eng, con = world
        sql = (
            "SELECT d1.d_year, d2.d_citykey, lo_revenue FROM lineorder "
            "JOIN dates d1 ON lo_orderdate = d1.d_datekey "
            "JOIN dates d2 ON d1.d_datekey = d2.d_datekey "
            "WHERE lo_revenue > 9500 "
            "ORDER BY lo_revenue, d1.d_year, d2.d_citykey LIMIT 15"
        )
        got = [(int(a), int(b), int(c)) for a, b, c in eng.query(sql).rows]
        want = [(int(a), int(b), int(c)) for a, b, c in con.execute(sql).fetchall()]
        assert got == want

    def test_self_join_requires_alias(self, world):
        eng, _ = world
        from pinot_tpu.mse.plan import JoinPlanError

        with pytest.raises((JoinPlanError, ValueError)):
            eng.query(
                "SELECT COUNT(*) FROM lineorder "
                "JOIN dates ON lo_orderdate = d_datekey "
                "JOIN dates ON lo_orderdate = d_datekey"
            )

    def test_three_level_chain(self, world):
        eng, con = world
        # per-year revenue through the chain, grouped on the MIDDLE dim
        sql = (
            "SELECT d_year, SUM(lo_revenue) FROM lineorder "
            "JOIN dates ON lo_orderdate = d_datekey "
            "JOIN city ON d_citykey = c_citykey "
            "WHERE c_region = 'region2' GROUP BY d_year ORDER BY d_year"
        )
        got = [(int(a), int(b)) for a, b in eng.query(sql + " LIMIT 20").rows]
        want = [(int(a), int(b)) for a, b in con.execute(sql).fetchall()]
        assert got == want
