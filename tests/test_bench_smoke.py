"""Smoke-run bench.py end to end on a small row count, both scan backends.

This is the in-image gate for the headline bench: it must run, ride the
range index, and emit one parseable JSON line carrying the round-6 fields
(`backend`, `effective_bytes_per_sec`) alongside the round-5 schema.
Marked slow — tier-1 runs with `-m 'not slow'`; CI or a human runs
`pytest -m slow` before publishing numbers."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["xla", "interpret"])
def test_bench_emits_json_with_bandwidth_fields(backend):
    env = dict(os.environ)
    env.update(
        {
            "BENCH_ROWS": str(1 << 20),
            "PINOT_TPU_SCAN_BACKEND": backend,
            "JAX_PLATFORMS": "cpu",
        }
    )
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        cwd=REPO,
        timeout=600,
        check=True,
    )
    # the JSON line is the last non-empty stdout line
    line = [l for l in out.stdout.splitlines() if l.strip()][-1]
    rec = json.loads(line)
    assert rec["backend"] == backend
    assert rec["rows"] == 1 << 20
    assert rec["effective_bytes_per_sec"] > 0
    # derivation sanity: bytes/s = rows/s * bytes/row, with bytes/row
    # between the 2 needed columns' floor and a generous 64-byte ceiling
    bpr = rec["effective_bytes_per_sec"] / rec["value"]
    assert 4 <= bpr <= 64
    assert rec["filter_index_uses"], "bench filter must ride the range index"
