"""Threaded stress for utils.cache.LruCache: concurrent get/put/invalidate
must never raise, never exceed the configured bounds, and keep the byte
accounting consistent with the surviving entries — the invariants the
W010 race class would break."""
import threading

from pinot_tpu.utils.cache import LruCache


def _hammer(cache, n_threads, n_ops, keyspace, value_of):
    errors = []
    start = threading.Barrier(n_threads)

    def worker(seed):
        try:
            start.wait(timeout=10)
            for i in range(n_ops):
                k = (seed * 31 + i * 7) % keyspace
                op = (seed + i) % 4
                if op == 0:
                    cache.put(k, value_of(k))
                elif op == 1:
                    v = cache.get(k)
                    assert v is None or v == value_of(k)
                elif op == 2:
                    cache.invalidate(k)
                else:
                    cache.put(k, value_of(k))
                    len(cache)
                    k in cache
        except Exception as e:  # surfaced to the main thread below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "stress worker wedged (deadlock?)"
    return errors


def test_concurrent_get_put_respects_entry_bound():
    from pinot_tpu.utils.metrics import METRICS

    cache = LruCache(max_entries=32, name="stress.lru")
    errors = _hammer(
        cache, n_threads=8, n_ops=2000, keyspace=100, value_of=lambda k: [k] * 4
    )
    assert errors == []
    assert len(cache) <= 32
    assert cache.stats()["entries"] == len(cache)
    counters = METRICS.snapshot()["counters"]
    assert counters.get("stress.lru.evictions", 0) > 0, "stress must exercise eviction"
    assert counters.get("stress.lru.hits", 0) + counters.get("stress.lru.misses", 0) > 0


def test_concurrent_eviction_keeps_byte_accounting_consistent():
    cache = LruCache(max_bytes=4096, sizeof=lambda v: 256)
    errors = _hammer(
        cache, n_threads=6, n_ops=1500, keyspace=64, value_of=lambda k: ("v", k)
    )
    assert errors == []
    # quiesced: tracked bytes must equal the sum over surviving entries
    assert cache.bytes == 256 * len(cache)
    assert cache.bytes <= 4096
