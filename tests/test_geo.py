"""Geo function tests: ST_DISTANCE haversine + GEOGRID cell bucketing.

Reference model: ST_DISTANCE + the H3 index role (BaseH3IndexCreator);
GEOGRID is the documented quantized-grid stand-in (no H3 lib in-image).
"""
import math

import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

N = 5000
SF = (37.7749, -122.4194)


def _haversine(lat1, lng1, lat2, lng2):
    r = 6371008.8
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = math.radians(lat2 - lat1)
    dl = math.radians(lng2 - lng1)
    a = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * r * math.asin(math.sqrt(a))


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(83)
    schema = Schema(
        "pois",
        [
            FieldSpec("name", DataType.STRING),
            FieldSpec("lat", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("lng", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    data = {
        "name": np.array([f"p{i}" for i in range(N)], dtype=object),
        "lat": rng.uniform(37.0, 38.5, N),
        "lng": rng.uniform(-123.0, -121.5, N),
        "v": rng.integers(0, 100, N),
    }
    eng = QueryEngine()
    eng.register_table(schema)
    eng.add_segment("pois", build_segment(schema, data, "s0"))
    return eng, data


class TestStDistance:
    def test_selection_matches_python(self, env):
        eng, data = env
        res = eng.query(
            f"SELECT name, ST_DISTANCE(lat, lng, {SF[0]}, {SF[1]}) FROM pois ORDER BY name LIMIT 50"
        )
        by_name = {data["name"][i]: i for i in range(N)}
        for row in res.rows:
            i = by_name[row[0]]
            expected = _haversine(data["lat"][i], data["lng"][i], *SF)
            assert abs(row[1] - expected) < 1.0, row[0]  # within a meter

    def test_radius_filter(self, env):
        eng, data = env
        r = 25_000.0
        res = eng.query(f"SELECT COUNT(*) FROM pois WHERE ST_DISTANCE(lat, lng, {SF[0]}, {SF[1]}) < {r}")
        expected = sum(
            1 for i in range(N) if _haversine(data["lat"][i], data["lng"][i], *SF) < r
        )
        assert res.rows[0][0] == expected

    def test_distance_in_aggregation(self, env):
        eng, data = env
        res = eng.query(f"SELECT MIN(ST_DISTANCE(lat, lng, {SF[0]}, {SF[1]})) FROM pois")
        expected = min(_haversine(data["lat"][i], data["lng"][i], *SF) for i in range(N))
        assert abs(res.rows[0][0] - expected) < 1.0


class TestGeoGrid:
    def test_geogrid_groupby(self, env):
        eng, data = env
        res = eng.query("SELECT GEOGRID(lat, lng, 6), COUNT(*) FROM pois GROUP BY GEOGRID(lat, lng, 6) LIMIT 10000")
        n = 1 << 6
        expected = {}
        for i in range(N):
            cx = min(n - 1, max(0, int((data["lng"][i] + 180.0) / 360.0 * n)))
            cy = min(n - 1, max(0, int((data["lat"][i] + 90.0) / 180.0 * n)))
            cell = cy * n + cx
            expected[cell] = expected.get(cell, 0) + 1
        got = {int(r[0]): int(r[1]) for r in res.rows}
        assert got == expected
