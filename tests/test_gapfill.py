"""GAPFILL: post-reduce time-bucket gap filling.

Round-4 verdict missing #2.  Reference: pinot-core/.../core/query/reduce/
GapfillProcessor.java + SumAvgGapfillProcessor.java (FILL modes per
GapfillUtils).  sqlite has no gapfill, so goldens are hand-computed over a
deliberately sparse series.
"""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.sql.parser import SqlParseError, parse_query
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema


def _schema():
    return Schema(
        "ts",
        [
            FieldSpec("bucket", DataType.LONG),
            FieldSpec("device", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
        ],
    )


@pytest.fixture(scope="module")
def eng():
    # sparse series: device a has buckets 100,120,130; device b has 110,130
    data = {
        "bucket": np.array([100, 100, 120, 130, 110, 130, 90, 200], np.int64),
        "device": np.array(["a", "a", "a", "a", "b", "b", "a", "b"], object),
        "v": np.array([1, 2, 5, 7, 4, 6, 99, 99], np.int64),
    }
    e = QueryEngine()
    e.register_table(_schema())
    e.add_segment("ts", build_segment(_schema(), data, "s0"))
    return e


def test_gapfill_default_null_fill(eng):
    res = eng.query(
        "SELECT GAPFILL(bucket, 100, 140, 10), SUM(v) FROM ts "
        "WHERE device = 'a' GROUP BY bucket LIMIT 100"
    )
    assert res.rows == [
        (100, 3),   # 1 + 2
        (110, None),
        (120, 5),
        (130, 7),
    ]


def test_gapfill_previous_value(eng):
    res = eng.query(
        "SELECT GAPFILL(bucket, 100, 140, 10, FILL(SUM(v), 'FILL_PREVIOUS_VALUE')), "
        "SUM(v) FROM ts WHERE device = 'a' GROUP BY bucket LIMIT 100"
    )
    assert res.rows == [
        (100, 3),
        (110, 3),  # carried from bucket 100
        (120, 5),
        (130, 7),
    ]


def test_gapfill_timeserieson(eng):
    res = eng.query(
        "SELECT GAPFILL(bucket, 100, 140, 10, FILL(SUM(v), 'FILL_PREVIOUS_VALUE'), "
        "TIMESERIESON(device)), device, SUM(v) FROM ts "
        "GROUP BY bucket, device ORDER BY device, bucket LIMIT 100"
    )
    assert res.rows == [
        (100, "a", 3),
        (110, "a", 3),
        (120, "a", 5),
        (130, "a", 7),
        (100, "b", None),  # no previous value yet
        (110, "b", 4),
        (120, "b", 4),     # carried
        (130, "b", 6),
    ]


def test_gapfill_out_of_range_rows_dropped(eng):
    # bucket 90 (v=99) and 200 (v=99) lie outside [100, 140): never emitted,
    # and 90's value must not leak in via FILL_PREVIOUS_VALUE
    res = eng.query(
        "SELECT GAPFILL(bucket, 100, 140, 10, FILL(SUM(v), 'FILL_PREVIOUS_VALUE')), "
        "SUM(v) FROM ts WHERE device = 'a' GROUP BY bucket LIMIT 100"
    )
    buckets = [r[0] for r in res.rows]
    assert buckets == [100, 110, 120, 130]
    assert all(r[1] != 99 for r in res.rows)


def test_gapfill_alias_fill_target(eng):
    res = eng.query(
        "SELECT GAPFILL(bucket, 100, 140, 10, FILL(s, 'FILL_PREVIOUS_VALUE')), "
        "SUM(v) AS s, COUNT(*) FROM ts WHERE device = 'a' GROUP BY bucket LIMIT 100"
    )
    # SUM carries forward; COUNT (no FILL spec) defaults to NULL on gaps
    assert res.rows == [
        (100, 3, 2),
        (110, 3, None),
        (120, 5, 1),
        (130, 7, 1),
    ]


def test_gapfill_default_value_fill(eng):
    """FILL_DEFAULT_VALUE fills the column type's default (0 for numeric),
    not NULL (review-caught)."""
    res = eng.query(
        "SELECT GAPFILL(bucket, 100, 140, 10, FILL(SUM(v), 'FILL_DEFAULT_VALUE')), "
        "SUM(v) FROM ts WHERE device = 'a' GROUP BY bucket LIMIT 100"
    )
    assert res.rows == [
        (100, 3),
        (110, 0),
        (120, 5),
        (130, 7),
    ]


def test_gapfill_order_by_desc(eng):
    res = eng.query(
        "SELECT GAPFILL(bucket, 100, 140, 10), SUM(v) FROM ts "
        "WHERE device = 'a' GROUP BY bucket ORDER BY bucket DESC LIMIT 2"
    )
    assert res.rows == [(130, 7), (120, 5)]


def test_gapfill_parse_errors():
    with pytest.raises(SqlParseError, match="step must be positive"):
        parse_query("SELECT GAPFILL(b, 0, 10, 0), SUM(v) FROM t GROUP BY b")
    with pytest.raises(SqlParseError, match="FILL mode"):
        parse_query(
            "SELECT GAPFILL(b, 0, 10, 1, FILL(SUM(v), 'FILL_SIDEWAYS')), SUM(v) "
            "FROM t GROUP BY b"
        )
    with pytest.raises(SqlParseError, match="GAPFILL requires"):
        parse_query("SELECT GAPFILL(b, 0, 10), SUM(v) FROM t GROUP BY b")


def test_gapfill_unselected_fill_target_errors(eng):
    with pytest.raises(Exception, match="not in the select list"):
        eng.query(
            "SELECT GAPFILL(bucket, 100, 140, 10, FILL(MAX(v), 'FILL_PREVIOUS_VALUE')), "
            "SUM(v) FROM ts GROUP BY bucket LIMIT 10"
        )
