"""2-D (replica x shard) mesh parity: every topology of the 8-device CPU
mesh must produce BIT-IDENTICAL results to the legacy 1-D "seg" mesh.

The hierarchical combine (shard/ICI psum first, then the replica/DCN
reduce — parallel/mesh.combine_hierarchical) re-associates the reduction,
and integer aggregates plus order-insensitive float partials make that
re-association exact: same rows, same float BITS, on 8x1, 2x4, 4x2 and the
1-D mesh.  This is the acceptance gate for the scale-out refactor — a
topology that drifts by one ulp means the combine reduced over the wrong
axis subset.
"""
import sqlite3
import struct

import numpy as np
import pytest

from pinot_tpu.parallel.engine import DistributedEngine, ReplicatedEngine
from pinot_tpu.parallel.mesh import default_mesh, make_mesh2d, replica_rows
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

TOPOLOGIES = [(8, 1), (2, 4), (4, 2)]

QUERIES = [
    # scans: scalar aggregates over every combine op (psum/pmin/pmax)
    "SELECT COUNT(*), SUM(m), MIN(m), MAX(m) FROM t",
    "SELECT COUNT(*), AVG(price), MIN(price), MAX(price) FROM t WHERE m > 250",
    # dense group-by (psum-combined group table)
    "SELECT k, COUNT(*), SUM(m) FROM t GROUP BY k ORDER BY k LIMIT 100",
    # string dictionary group-by + float aggregate
    "SELECT s, SUM(price), COUNT(*) FROM t GROUP BY s ORDER BY s LIMIT 10",
    # sparse group-by path (per-device scatter tables, host merge)
    "SET maxDenseGroups = 16; "
    "SELECT k, SUM(m) FROM t GROUP BY k ORDER BY k LIMIT 100",
    # MSE star join through the exchange (broadcast + shuffle below)
    "SELECT dv, COUNT(*), SUM(m) FROM t JOIN d ON k = dk GROUP BY dv ORDER BY dv LIMIT 20",
]


def _bits(v):
    """Float values compare by BIT PATTERN — parity means identical bits,
    not merely approximately-equal values."""
    if isinstance(v, float):
        return struct.pack("<d", v).hex()
    return v


def _canon(res):
    return [tuple(_bits(v) for v in row) for row in res.rows]


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(17)
    n = 8192
    schema = Schema(
        name="t",
        fields=[
            FieldSpec("k", DataType.INT),
            FieldSpec("m", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("price", DataType.DOUBLE, role=FieldRole.METRIC),
            FieldSpec("s", DataType.STRING),
        ],
    )
    data = {
        "k": rng.integers(0, 64, n).astype(np.int64),
        "m": rng.integers(1, 500, n).astype(np.int64),
        "price": np.round(rng.uniform(0.5, 99.5, n), 2),
        "s": rng.choice(["asia", "europe", "americas"], n),
    }
    dim_schema = Schema(
        name="d",
        fields=[FieldSpec("dk", DataType.INT), FieldSpec("dv", DataType.INT)],
    )
    dim = {"dk": np.arange(64, dtype=np.int64), "dv": (np.arange(64) % 7).astype(np.int64)}
    return schema, data, dim_schema, dim


def _engine(dataset, mesh):
    schema, data, dim_schema, dim = dataset
    eng = DistributedEngine(mesh)
    eng.register_table("t", StackedTable.build(schema, data, 8))
    eng.register_table("d", StackedTable.build(dim_schema, dim, 8))
    return eng


def _run_all(eng):
    out = []
    for q in QUERIES:
        if "JOIN" in q:
            for strat in ("broadcast", "shuffle"):
                out.append(_canon(eng.query(f"SET joinStrategy = '{strat}'; " + q)))
        else:
            out.append(_canon(eng.query(q)))
    return out


@pytest.fixture(scope="module")
def baseline(dataset):
    """The legacy 1-D 8-device mesh is the reference everything must match."""
    return _run_all(_engine(dataset, default_mesh()))


@pytest.mark.parametrize("topology", TOPOLOGIES, ids=lambda t: f"{t[0]}x{t[1]}")
def test_topology_bit_parity(dataset, baseline, topology):
    r, s = topology
    got = _run_all(_engine(dataset, make_mesh2d(r, s)))
    assert got == baseline, f"results drifted on the {r}x{s} mesh"


def test_baseline_matches_sqlite(dataset):
    """Anchor the parity chain to an external reference: the 1-D baseline's
    integer group-by agrees with sqlite, so bit-parity above is parity with
    the RIGHT answer, not a shared bug."""
    schema, data, dim_schema, dim = dataset
    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE t (k, m, price, s)")
    con.executemany(
        "INSERT INTO t VALUES (?,?,?,?)",
        list(zip(*(np.asarray(data[c]).tolist() for c in ("k", "m", "price", "s")))),
    )
    exp = con.execute("SELECT k, COUNT(*), SUM(m) FROM t GROUP BY k ORDER BY k").fetchall()
    con.close()
    res = _engine(dataset, default_mesh()).query(QUERIES[2])
    got = [(int(a), int(b), int(c)) for a, b, c in res.rows]
    assert got == [(int(a), int(b), int(c)) for a, b, c in exp]


def test_replicated_engine_rows_agree(dataset):
    """QPS tier: each replica row holds a full copy on its own 1-D submesh;
    consecutive queries round-robin across rows and must agree bitwise."""
    schema, data, dim_schema, dim = dataset
    rep = ReplicatedEngine(num_replicas=2)
    assert rep.num_replicas == 2
    rep.register_table("t", StackedTable.build(schema, data, 4))
    for q in QUERIES[:4]:
        first = _canon(rep.query(q))
        for _ in range(3):  # cycles the row rotation at least once
            assert _canon(rep.query(q)) == first
    # per-row residency managers are row-local (budget split, no sharing)
    managers = {id(e.residency) for e in rep.engines if e.residency is not None}
    assert len(managers) == len([e for e in rep.engines if e.residency is not None])


def test_replicated_engine_coordinator_placement(dataset):
    """mesh_placement maps replica groups onto mesh rows; a row whose
    backing servers are all dead drops out of the routing rotation."""
    from pinot_tpu.cluster.coordinator import Coordinator
    from pinot_tpu.cluster.server import ServerInstance

    schema, data, dim_schema, dim = dataset
    coord = Coordinator(replication=2)
    for name in ("s0", "s1"):
        coord.register_server(ServerInstance(name))
    placement = coord.mesh_placement(2)
    assert set(placement) == {0, 1}
    assert sorted(placement[0] + placement[1]) == ["s0", "s1"]

    rep = ReplicatedEngine(num_replicas=2, coordinator=coord)
    rep.register_table("t", StackedTable.build(schema, data, 4))
    dead_row = coord.replica_group["s1"] % 2
    coord.mark_down("s1")
    assert coord.mesh_placement(2)[dead_row] == []
    live_row = 1 - dead_row
    # every routed query must land on the surviving row
    for _ in range(4):
        assert rep._next_row() == live_row
    r = rep.query("SELECT COUNT(*) FROM t")
    assert int(r.rows[0][0]) == len(data["k"])


def test_mesh2d_divisibility_error():
    with pytest.raises(ValueError, match="divisible"):
        make_mesh2d(3)  # 8 devices don't factor into 3 replica rows
    with pytest.raises(ValueError, match="devices"):
        make_mesh2d(2, 3)  # 2x3 != 8


def test_replica_rows_shapes():
    rows = replica_rows(make_mesh2d(2, 4))
    assert len(rows) == 2
    assert all(tuple(m.axis_names) == ("shard",) for m in rows)
    assert all(int(np.prod(m.devices.shape)) == 4 for m in rows)
    # the rows partition the parent's devices disjointly
    ids = [d.id for m in rows for d in m.devices.flat]
    assert len(ids) == len(set(ids)) == 8


def test_dryrun_multichip_topologies():
    """The driver entry point exercises the same paths per topology."""
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
    ge.dryrun_multichip(8, topology=(2, 4))
    ge.dryrun_multichip(8, topology=(4, 2))
