"""Fault-tolerant scatter-gather tests: replica failover, circuit breaker,
partial results, deadline propagation, REST error-code parity — all driven
by the deterministic cluster.faults.FaultPlan harness (no sleeps, no luck).
"""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pinot_tpu.cluster import (
    Broker,
    Coordinator,
    FaultPlan,
    NoReplicaAvailableError,
    ServerFaultError,
    ServerHealth,
    ServerInstance,
)
from pinot_tpu.query.safety import Deadline, QueryTimeoutError
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import SegmentsConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


def _data(n, seed, t0=1_700_000_000_000):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(["sf", "nyc", "la"], n).astype(object),
        "v": rng.integers(0, 100, n),
        "ts": t0 + rng.integers(0, 86_400_000, n).astype(np.int64),
    }


def _cluster(n_servers=3, replication=2, n_segments=4, rows=300):
    """Deterministic cluster: same args -> identical assignment + data."""
    coord = Coordinator(replication=replication)
    for i in range(n_servers):
        coord.register_server(ServerInstance(f"server{i}"))
    coord.add_table(_schema(), TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    datas = []
    for i in range(n_segments):
        d = _data(rows, seed=100 + i)
        datas.append(d)
        coord.add_segment("t", build_segment(_schema(), d, f"seg{i}"))
    merged = {k: np.concatenate([d[k] for d in datas]) for k in datas[0]}
    return coord, merged


QUERIES = [
    "SELECT COUNT(*), SUM(v) FROM t",
    "SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city ORDER BY city",
]


class TestDeadlineRegression:
    def test_zero_timeout_is_already_expired(self):
        """timeoutMs=0 used to be falsy and silently DISABLED the deadline."""
        d = Deadline(0)
        assert d.expired()
        with pytest.raises(QueryTimeoutError, match="timeoutMs=0"):
            d.check()

    def test_none_never_expires(self):
        d = Deadline(None)
        assert not d.expired()
        assert d.remaining_ms() is None
        d.check()

    def test_bounded_child_deadline(self):
        parent = Deadline(60_000)
        child = parent.bounded(10.0)
        assert child.remaining_ms() <= 10.0
        # unbounded parent + cap -> cap; unbounded both -> unbounded
        assert Deadline(None).bounded(5.0).timeout_ms == 5.0
        assert Deadline(None).bounded(None).remaining_ms() is None


class TestReplicaFailover:
    def test_server_killed_mid_scatter_exact_rows(self):
        """A seeded FaultPlan kills a server on its first scatter call; the
        broker re-routes its segments to surviving replicas and the result
        matches the no-fault run exactly."""
        # replication == n_servers: every segment lives on both servers, so
        # server0 is routed some segments in EVERY query (deterministic kill)
        coord_ok, merged = _cluster(n_servers=2, replication=2)
        baseline = {sql: Broker(coord_ok).query(sql).rows for sql in QUERIES}
        conn = sqlite_from_data("t", merged)

        coord, _ = _cluster(n_servers=2, replication=2)
        plan = FaultPlan(seed=7).fail_server("server0", on_call=1).attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None  # no real backoff waits in tests
        for sql in QUERIES:
            res = broker.query(sql)
            assert_same_rows(res.rows, baseline[sql])
            assert_same_rows(res.rows, conn.execute(sql).fetchall())
            # failover absorbed the crash: never partial, never zero servers
            assert res.stats.partial_result is False
            assert res.stats.num_servers_responded >= 1
        # the injected kill actually fired and was recorded
        assert any(entry[2] == "fail" for entry in plan.log)
        assert plan.calls("server0") >= 1

    def test_dropped_segment_fails_over(self):
        """A server that lost a local segment copy (KeyError) triggers
        failover for just that server's segments."""
        coord_ok, merged = _cluster()
        baseline = Broker(coord_ok).query(QUERIES[0]).rows
        coord, _ = _cluster()
        FaultPlan(seed=3).drop_segment("server0", "t", "seg0").attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        for _ in range(4):  # whatever replica rotation picks server0 for seg0
            res = broker.query(QUERIES[0])
            assert_same_rows(res.rows, baseline)

    def test_chaos_is_deterministic(self):
        """Two identically-seeded plans on identically-built clusters produce
        byte-identical responses — the reproducibility contract."""

        def run(seed):
            coord, _ = _cluster(n_servers=4, replication=2, n_segments=6)
            FaultPlan(seed=seed).chaos([f"server{i}" for i in range(4)], p_fail=0.4).attach(coord)
            broker = Broker(coord)
            broker._sleep = lambda s: None
            res = broker.query(
                "SET allowPartialResults = true; SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city ORDER BY city"
            )
            return res.rows, res.stats.partial_result, len(res.stats.exceptions)

        assert run(1234) == run(1234)

    def test_failover_exhaustion_raises_without_partial_optin(self):
        coord, _ = _cluster(n_servers=2, replication=1, n_segments=2)
        FaultPlan(seed=1).always_fail("server0").always_fail("server1").attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        with pytest.raises(RuntimeError, match="no live replica|failed on every"):
            broker.query(QUERIES[0])


class TestPartialResults:
    def _partial_cluster(self):
        """replication=1, one server permanently dead: its segments have no
        surviving replica, the other server's segments still answer."""
        coord, merged = _cluster(n_servers=2, replication=1, n_segments=4)
        FaultPlan(seed=11).always_fail("server0", message="injected crash").attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        return coord, broker

    def test_partial_response_metadata(self):
        coord, broker = self._partial_cluster()
        res = broker.query("SET allowPartialResults = true; SELECT COUNT(*) FROM t")
        s = res.stats
        assert s.partial_result is True
        assert s.exceptions and any("server0" in str(e) for e in s.exceptions)
        assert s.num_servers_responded < s.num_servers_queried
        # surviving segments' rows are complete and correct
        live_docs = sum(
            seg.num_docs for seg in coord.servers["server1"].segments["t"].values()
        )
        assert int(res.rows[0][0]) == live_docs > 0

    def test_without_optin_raises_cleanly(self):
        _, broker = self._partial_cluster()
        with pytest.raises(RuntimeError, match="no live replica"):
            broker.query("SELECT COUNT(*) FROM t")

    def test_all_replicas_marked_down(self):
        """Liveness-down (not crash) replicas: partialResult path through
        unroutable segments."""
        coord, _ = _cluster(n_servers=2, replication=1, n_segments=4)
        # kill server0 mid-scatter via a flap triggered by server1's call,
        # so server0 was queried (and fails), then has no live replica left
        plan = FaultPlan(seed=5)
        plan.always_fail("server0").flap_down("server0", on_call=1, of="server1").attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        res = broker.query("SET allowPartialResults = true; SELECT COUNT(*) FROM t")
        assert res.stats.partial_result is True
        assert any(e["errorCode"] == "NO_REPLICA_AVAILABLE" for e in res.stats.exceptions) or any(
            e["errorCode"] == "PARTIAL_RESPONSE" for e in res.stats.exceptions
        )
        with pytest.raises(RuntimeError):
            broker.query("SELECT COUNT(*) FROM t")


class TestCircuitBreaker:
    def test_quarantine_then_half_open_probe(self):
        clk = [0.0]
        coord, merged = _cluster(n_servers=2, replication=2, n_segments=4)
        plan = FaultPlan(seed=2).fail_server("server0", on_call=1, times=3).attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        broker.health.clock = lambda: clk[0]
        broker.health.cooldown_s = 30.0
        conn = sqlite_from_data("t", merged)
        # 3 consecutive failures trip the breaker (queries stay correct)
        for _ in range(3):
            assert_same_rows(broker.query(QUERIES[0]).rows, conn.execute(QUERIES[0]).fetchall())
        assert broker.health.state("server0") == "open"
        calls_when_opened = plan.calls("server0")
        # quarantined: receives NO routes while healthy replicas exist
        for _ in range(3):
            broker.query(QUERIES[0])
        assign, _ = broker._route("t", [f"seg{i}" for i in range(4)], partial_ok=True)
        assert "server0" not in assign
        assert plan.calls("server0") == calls_when_opened
        # cooldown elapses -> half-open -> one probe goes through and (fault
        # exhausted after 3 calls) succeeds -> breaker closes
        clk[0] += 31.0
        assert broker.health.state("server0") == "half_open"
        for _ in range(4):
            broker.query(QUERIES[0])
        assert plan.calls("server0") > calls_when_opened
        assert broker.health.state("server0") == "closed"
        assert broker.health.consecutive_failures("server0") == 0

    def test_failed_probe_reopens(self):
        clk = [0.0]
        h = ServerHealth(failure_threshold=2, cooldown_s=10.0)
        h.clock = lambda: clk[0]
        h.record_failure("s"); h.record_failure("s")
        assert h.state("s") == "open" and not h.available("s")
        clk[0] = 11.0
        assert h.state("s") == "half_open" and h.available("s")
        h.begin_probe("s")
        assert not h.available("s")  # single-flight probe
        h.record_failure("s")  # probe failed: re-quarantine, fresh cooldown
        assert h.state("s") == "open" and not h.available("s")
        clk[0] = 22.0
        h.begin_probe("s")
        h.record_success("s")
        assert h.state("s") == "closed" and h.available("s")

    def test_coordinator_mark_up_resets_breaker(self):
        coord, _ = _cluster(n_servers=2, replication=2, n_segments=2)
        broker = Broker(coord)
        for _ in range(3):
            broker.health.record_failure("server0")
        assert broker.health.state("server0") == "open"
        coord.mark_down("server0")
        coord.mark_up("server0")  # recovery (heartbeat re-establishment)
        assert broker.health.state("server0") == "closed"


class TestDeadlinePropagation:
    def test_server_checks_deadline_between_kernels(self):
        coord, _ = _cluster(n_servers=1, replication=1, n_segments=3)
        srv = coord.servers["server0"]
        from pinot_tpu.sql.parser import parse_query

        ctx = parse_query("SELECT COUNT(*) FROM t")
        with pytest.raises(QueryTimeoutError, match="out of query budget"):
            srv.execute(ctx, srv.segment_names("t"), deadline=Deadline(0))

    def test_per_server_timeout_fails_over(self):
        """A slow replica (injected latency) blows its per-server budget but
        NOT the query deadline: its segments fail over and rows stay exact."""
        coord_ok, merged = _cluster(n_servers=2, replication=2)
        baseline = Broker(coord_ok).query(QUERIES[0]).rows
        coord, _ = _cluster(n_servers=2, replication=2)
        plan = FaultPlan(seed=9).attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        sql = "SET serverTimeoutMs = 50; SET timeoutMs = 60000; SELECT COUNT(*), SUM(v) FROM t"
        # warm up with the IDENTICAL query before arming the fault: compiles
        # this exact plan + ships segments, so the faulted run below measures
        # only the injected latency against the per-server cap
        assert_same_rows(broker.query(sql).rows, baseline)
        plan.add_latency("server0", ms=150)
        res = broker.query(sql)
        assert_same_rows(res.rows, baseline)
        assert any(e["errorCode"] == "EXECUTION_TIMEOUT_ERROR" for e in res.stats.exceptions)
        assert res.stats.partial_result is False

    def test_query_deadline_still_raises(self):
        coord, _ = _cluster(n_servers=2, replication=2, n_segments=2)
        broker = Broker(coord)
        with pytest.raises(QueryTimeoutError):
            broker.query("SET timeoutMs = 0; SELECT COUNT(*) FROM t")


class TestRestFaultSurface:
    def _post(self, port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/query/sql",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    def test_timeout_maps_to_408(self):
        from pinot_tpu.cluster.rest import QueryServer

        coord, _ = _cluster(n_servers=1, replication=1, n_segments=1)
        srv = QueryServer(Broker(coord)).start()
        try:
            code, payload = self._post(srv.port, {"sql": "SET timeoutMs = 0; SELECT COUNT(*) FROM t"})
            assert code == 408 and payload["errorCode"] == "EXECUTION_TIMEOUT_ERROR"
        finally:
            srv.stop()

    def test_admission_maps_to_503(self):
        from pinot_tpu.cluster.rest import QueryServer
        from pinot_tpu.query.engine import QueryEngine

        eng = QueryEngine(memory_budget_bytes=512)  # nothing fits
        eng.register_table(_schema())
        eng.add_segment("t", build_segment(_schema(), _data(2000, seed=1), "s0"))
        srv = QueryServer(eng).start()
        try:
            code, payload = self._post(srv.port, {"sql": "SELECT SUM(v) FROM t"})
            assert code == 503 and payload["errorCode"] == "SERVER_RESOURCE_LIMIT_EXCEEDED"
        finally:
            srv.stop()

    def test_partial_result_surfaced_in_broker_response(self):
        from pinot_tpu.cluster.rest import QueryServer

        coord, _ = _cluster(n_servers=2, replication=1, n_segments=4)
        FaultPlan(seed=4).always_fail("server0").attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        srv = QueryServer(broker).start()
        try:
            code, payload = self._post(
                srv.port, {"sql": "SET allowPartialResults = true; SELECT COUNT(*) FROM t"}
            )
            assert code == 200
            assert payload["partialResult"] is True
            assert payload["exceptions"]
            assert payload["numServersResponded"] < payload["numServersQueried"]
        finally:
            srv.stop()

    def test_scatter_error_maps_to_500_with_exceptions(self):
        from pinot_tpu.cluster.rest import QueryServer

        # maxScatterRetries=0: the first failed round exhausts failover even
        # though a healthy replica remains -> ScatterGatherError surface
        coord, _ = _cluster(n_servers=2, replication=2, n_segments=4)
        FaultPlan(seed=4).always_fail("server0").attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        srv = QueryServer(broker).start()
        try:
            code, payload = self._post(
                srv.port, {"sql": "SET maxScatterRetries = 0; SELECT COUNT(*) FROM t"}
            )
            assert code == 500 and payload["errorCode"] == "SERVER_SCATTER_ERROR"
            assert payload["exceptions"]
        finally:
            srv.stop()

    def test_no_replica_maps_to_503(self):
        from pinot_tpu.cluster.rest import QueryServer

        coord, _ = _cluster(n_servers=2, replication=1, n_segments=4)
        FaultPlan(seed=4).always_fail("server0").attach(coord)
        broker = Broker(coord)
        broker._sleep = lambda s: None
        srv = QueryServer(broker).start()
        try:
            code, payload = self._post(srv.port, {"sql": "SELECT COUNT(*) FROM t"})
            assert code == 503 and payload["errorCode"] == "NO_REPLICA_AVAILABLE"
        finally:
            srv.stop()


class TestFaultPlanHarness:
    def test_call_counters_and_log(self):
        coord, _ = _cluster(n_servers=2, replication=2, n_segments=2)
        plan = FaultPlan(seed=0).add_latency("server0", ms=0.0, on_call=1).attach(coord)
        broker = Broker(coord)
        broker.query(QUERIES[0])
        assert plan.calls("server0") + plan.calls("server1") >= 1
        assert all(len(entry) == 4 for entry in plan.log)

    def test_fail_rule_raises_server_fault(self):
        srv = ServerInstance("s0")
        srv.fault_plan = FaultPlan(seed=0).fail_server("s0", on_call=1)
        from pinot_tpu.sql.parser import parse_query

        with pytest.raises(ServerFaultError, match="injected fault"):
            srv.execute(parse_query("SELECT COUNT(*) FROM t"), [])

    def test_flap_rules_drive_coordinator(self):
        coord, _ = _cluster(n_servers=2, replication=2, n_segments=2)
        plan = FaultPlan(seed=0)
        plan.flap_down("server1", on_call=1, of="server0")
        plan.flap_up("server1", on_call=2, of="server0")
        plan.attach(coord)
        plan.on_execute("server0")  # server0's 1st call downs server1
        assert "server1" not in coord.live
        plan.on_execute("server0")  # 2nd call brings it back
        assert "server1" in coord.live
