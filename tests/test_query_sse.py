"""M1 tests: single-stage query engine over hand-built QueryContext IR,
golden-checked against sqlite3 (multi-segment, heterogeneous dictionaries)."""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.query.ir import (
    AggregationSpec,
    Expr,
    FilterNode,
    OrderByExpr,
    Predicate,
    PredicateType,
    QueryContext,
)
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import IndexingConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

from golden import assert_same_rows, sqlite_from_data

N = 5000
CITIES = ["sf", "nyc", "chi", "la", "sea", "pdx", "atx"]


def _make_data(seed, n=N):
    rng = np.random.default_rng(seed)
    return {
        "city": rng.choice(CITIES, n).astype(object),
        "year": rng.integers(2000, 2024, n).astype(np.int32),
        "v": rng.integers(-50, 1000, n),
        "price": np.where(rng.random(n) < 0.15, np.nan, np.round(rng.random(n) * 100, 3)),
    }


def _schema():
    return Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("year", DataType.INT),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("price", DataType.DOUBLE, role=FieldRole.METRIC, nullable=True),
        ],
    )


@pytest.fixture(scope="module")
def setup():
    schema = _schema()
    cfg = TableConfig("t", indexing=IndexingConfig(inverted_index_columns=["city"], range_index_columns=["year"]))
    engine = QueryEngine()
    engine.register_table(schema, cfg)
    # 3 segments with different data → heterogeneous per-segment dictionaries
    all_data = {k: [] for k in ("city", "year", "v", "price")}
    for i, seed in enumerate([1, 2, 3]):
        data = _make_data(seed, N)
        if i == 2:  # make segment 2's city dictionary differ
            data["city"][:100] = "den"
        seg = build_segment(schema, data, f"seg{i}", table_config=cfg)
        engine.add_segment("t", seg)
        for k in all_data:
            all_data[k].append(data[k])
    merged = {k: np.concatenate(v) for k, v in all_data.items()}
    nulls = {"price": np.isnan(merged["price"])}
    conn = sqlite_from_data("t", merged, nulls)
    return engine, conn


def agg(fn, col=None, **kw):
    return AggregationSpec(fn, Expr.col(col) if col else None, **kw)


def P(ptype, col, *values, **kw):
    return FilterNode.pred(Predicate(PredicateType[ptype], Expr.col(col), tuple(values), **kw))


def run_ctx(setup, ctx, sql, ordered=False):
    engine, conn = setup
    res = engine.execute(ctx)
    expected = conn.execute(sql).fetchall()
    assert_same_rows(res.rows, expected, ordered=ordered)
    return res


class TestAggregation:
    def test_count_star(self, setup):
        ctx = QueryContext("t", [agg("count")])
        run_ctx(setup, ctx, "SELECT COUNT(*) FROM t")

    def test_sum_min_max_avg(self, setup):
        ctx = QueryContext("t", [agg("sum", "v"), agg("min", "v"), agg("max", "v"), agg("avg", "v")])
        run_ctx(setup, ctx, "SELECT SUM(v), MIN(v), MAX(v), AVG(v) FROM t")

    def test_agg_with_range_filter(self, setup):
        ctx = QueryContext(
            "t",
            [agg("sum", "v"), agg("count")],
            filter=P("RANGE", "year", lower=2010, lower_inclusive=False),
        )
        run_ctx(setup, ctx, "SELECT SUM(v), COUNT(*) FROM t WHERE year > 2010")

    def test_agg_with_eq_string_filter(self, setup):
        ctx = QueryContext("t", [agg("sum", "v")], filter=P("EQ", "city", "sf"))
        run_ctx(setup, ctx, "SELECT SUM(v) FROM t WHERE city = 'sf'")

    def test_agg_nullable_column(self, setup):
        ctx = QueryContext("t", [agg("sum", "price"), agg("count", "price"), agg("avg", "price")])
        run_ctx(setup, ctx, "SELECT SUM(price), COUNT(price), AVG(price) FROM t")

    def test_empty_match_null_semantics(self, setup):
        ctx = QueryContext("t", [agg("sum", "v"), agg("count"), agg("min", "v")], filter=P("EQ", "city", "zzz"))
        run_ctx(setup, ctx, "SELECT SUM(v), COUNT(*), MIN(v) FROM t WHERE city = 'zzz'")

    def test_and_or_not(self, setup):
        f = FilterNode.and_(
            FilterNode.or_(P("EQ", "city", "sf"), P("EQ", "city", "nyc")),
            FilterNode.not_(P("RANGE", "year", upper=2010, upper_inclusive=False)),
        )
        ctx = QueryContext("t", [agg("count")], filter=f)
        run_ctx(setup, ctx, "SELECT COUNT(*) FROM t WHERE (city='sf' OR city='nyc') AND NOT (year < 2010)")

    def test_in_notin(self, setup):
        ctx = QueryContext("t", [agg("count")], filter=P("IN", "city", "sf", "den", "zzz"))
        run_ctx(setup, ctx, "SELECT COUNT(*) FROM t WHERE city IN ('sf','den','zzz')")
        ctx = QueryContext("t", [agg("count")], filter=P("NOT_IN", "city", "sf", "den"))
        run_ctx(setup, ctx, "SELECT COUNT(*) FROM t WHERE city NOT IN ('sf','den')")

    def test_range_on_raw_metric(self, setup):
        ctx = QueryContext("t", [agg("count"), agg("avg", "v")], filter=P("RANGE", "v", lower=0, upper=500))
        run_ctx(setup, ctx, "SELECT COUNT(*), AVG(v) FROM t WHERE v BETWEEN 0 AND 500")

    def test_regexp_like(self, setup):
        ctx = QueryContext("t", [agg("count")], filter=P("REGEXP_LIKE", "city", "^s"))
        run_ctx(setup, ctx, "SELECT COUNT(*) FROM t WHERE city LIKE 's%'")

    def test_is_null(self, setup):
        ctx = QueryContext("t", [agg("count")], filter=P("IS_NULL", "price"))
        run_ctx(setup, ctx, "SELECT COUNT(*) FROM t WHERE price IS NULL")
        ctx = QueryContext("t", [agg("count")], filter=P("IS_NOT_NULL", "price"))
        run_ctx(setup, ctx, "SELECT COUNT(*) FROM t WHERE price IS NOT NULL")

    def test_expression_agg(self, setup):
        ctx = QueryContext("t", [AggregationSpec("sum", Expr.call("times", Expr.col("v"), Expr.lit(2)))])
        run_ctx(setup, ctx, "SELECT SUM(v * 2) FROM t")

    def test_filtered_aggregation(self, setup):
        ctx = QueryContext(
            "t",
            [AggregationSpec("sum", Expr.col("v"), filter=P("EQ", "city", "sf")), agg("count")],
        )
        run_ctx(setup, ctx, "SELECT SUM(v) FILTER (WHERE city='sf'), COUNT(*) FROM t")

    def test_variance_stddev(self, setup):
        engine, conn = setup
        ctx = QueryContext("t", [agg("variance", "v"), agg("stddev", "v")])
        res = engine.execute(ctx)
        vals = [r[0] for r in conn.execute("SELECT v FROM t").fetchall()]
        assert res.rows[0][0] == pytest.approx(np.var(vals), rel=1e-9)
        assert res.rows[0][1] == pytest.approx(np.std(vals), rel=1e-9)


class TestGroupBy:
    def test_groupby_string(self, setup):
        ctx = QueryContext("t", [Expr.col("city"), agg("sum", "v")], group_by=[Expr.col("city")], limit=100)
        run_ctx(setup, ctx, "SELECT city, SUM(v) FROM t GROUP BY city")

    def test_groupby_two_dims(self, setup):
        ctx = QueryContext(
            "t",
            [Expr.col("city"), Expr.col("year"), agg("count"), agg("avg", "v")],
            group_by=[Expr.col("city"), Expr.col("year")],
            limit=1000,
        )
        run_ctx(setup, ctx, "SELECT city, year, COUNT(*), AVG(v) FROM t GROUP BY city, year")

    def test_groupby_with_filter(self, setup):
        ctx = QueryContext(
            "t",
            [Expr.col("year"), agg("sum", "v")],
            filter=P("EQ", "city", "sf"),
            group_by=[Expr.col("year")],
            limit=100,
        )
        run_ctx(setup, ctx, "SELECT year, SUM(v) FROM t WHERE city='sf' GROUP BY year")

    def test_groupby_having(self, setup):
        # HAVING references the agg by structure: sum(v)
        agg_spec = AggregationSpec("sum", Expr.col("v"))
        having = FilterNode.pred(
            Predicate(PredicateType.RANGE, Expr.call("sum", Expr.col("v")), lower=60000, lower_inclusive=False)
        )
        ctx = QueryContext(
            "t",
            [Expr.col("city"), agg_spec],
            group_by=[Expr.col("city")],
            having=having,
            limit=100,
        )
        run_ctx(setup, ctx, "SELECT city, SUM(v) FROM t GROUP BY city HAVING SUM(v) > 60000")

    def test_groupby_order_limit(self, setup):
        ctx = QueryContext(
            "t",
            [Expr.col("city"), agg("sum", "v")],
            group_by=[Expr.col("city")],
            order_by=[OrderByExpr(Expr.call("sum", Expr.col("v")), ascending=False)],
            limit=3,
        )
        run_ctx(setup, ctx, "SELECT city, SUM(v) FROM t GROUP BY city ORDER BY SUM(v) DESC LIMIT 3", ordered=True)

    def test_groupby_sparse_fallback(self, setup):
        # force the sparse path with a tiny dense-key-space bound
        ctx = QueryContext(
            "t",
            [Expr.col("city"), Expr.col("year"), agg("sum", "v"), agg("min", "v")],
            group_by=[Expr.col("city"), Expr.col("year")],
            limit=1000,
            options={"maxDenseGroups": 4},
        )
        run_ctx(setup, ctx, "SELECT city, year, SUM(v), MIN(v) FROM t GROUP BY city, year")

    def test_num_groups_limit_trims(self, setup):
        engine, _ = setup
        ctx = QueryContext(
            "t",
            [Expr.col("city"), Expr.col("year"), agg("sum", "v")],
            group_by=[Expr.col("city"), Expr.col("year")],
            limit=1000,
            options={"maxDenseGroups": 4, "numGroupsLimit": 7},
        )
        res = engine.execute(ctx)
        # valve caps tracked groups per segment; merged result stays bounded
        assert 0 < len(res.rows) <= 3 * 7

    def test_groupby_nullable_metric(self, setup):
        ctx = QueryContext("t", [Expr.col("city"), agg("avg", "price")], group_by=[Expr.col("city")], limit=100)
        run_ctx(setup, ctx, "SELECT city, AVG(price) FROM t GROUP BY city")


class TestSelection:
    def test_select_limit(self, setup):
        engine, conn = setup
        ctx = QueryContext("t", [Expr.col("city"), Expr.col("v")], limit=17)
        res = engine.execute(ctx)
        assert len(res.rows) == 17
        # rows must be a subset of the real data
        allowed = set(conn.execute("SELECT city, v FROM t").fetchall())
        for r in res.rows:
            assert (r[0], r[1]) in allowed

    def test_select_where_order_by(self, setup):
        ctx = QueryContext(
            "t",
            [Expr.col("city"), Expr.col("year"), Expr.col("v")],
            filter=P("EQ", "city", "nyc"),
            order_by=[OrderByExpr(Expr.col("v"), ascending=False), OrderByExpr(Expr.col("year"))],
            limit=10,
        )
        run_ctx(
            setup,
            ctx,
            "SELECT city, year, v FROM t WHERE city='nyc' ORDER BY v DESC, year LIMIT 10",
            ordered=True,
        )

    def test_select_order_by_string_across_segments(self, setup):
        ctx = QueryContext(
            "t",
            [Expr.col("city"), Expr.col("v")],
            order_by=[OrderByExpr(Expr.col("city")), OrderByExpr(Expr.col("v"))],
            limit=5,
        )
        run_ctx(setup, ctx, "SELECT city, v FROM t ORDER BY city, v LIMIT 5", ordered=True)

    def test_select_offset(self, setup):
        ctx = QueryContext(
            "t",
            [Expr.col("v")],
            order_by=[OrderByExpr(Expr.col("v"))],
            limit=5,
            offset=7,
        )
        run_ctx(setup, ctx, "SELECT v FROM t ORDER BY v LIMIT 5 OFFSET 7", ordered=True)


class TestPruning:
    def test_eq_prunes_all(self, setup):
        engine, conn = setup
        ctx = QueryContext("t", [agg("count")], filter=P("EQ", "city", "nowhere"))
        res = engine.execute(ctx)
        assert res.stats.num_segments_pruned == 3
        assert res.rows[0][0] == 0

    def test_range_prunes(self, setup):
        engine, _ = setup
        ctx = QueryContext("t", [agg("count")], filter=P("RANGE", "year", lower=3000))
        res = engine.execute(ctx)
        assert res.stats.num_segments_pruned == 3

    def test_den_only_in_one_segment(self, setup):
        engine, conn = setup
        ctx = QueryContext("t", [agg("count")], filter=P("EQ", "city", "den"))
        res = engine.execute(ctx)
        assert res.stats.num_segments_pruned == 2  # den exists only in seg2
        expected = conn.execute("SELECT COUNT(*) FROM t WHERE city='den'").fetchall()
        assert res.rows[0][0] == expected[0][0]
