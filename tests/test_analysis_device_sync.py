"""Host-device sync auditor (pinot_tpu.analysis.device_sync).

Fixture packages route taint from jnp.* sources into the W013/W014
sinks on a synthetic warm path; clean counterparts sanitize via
jax.device_get or stay off the warm path and must report nothing."""
import textwrap

from pinot_tpu.analysis.device_sync import DeviceSyncPass
from pinot_tpu.analysis.engine import Project, run_passes


def _findings(src, warm=("warm.py",), allowed=None, **extra):
    files = {"pkg/warm.py": textwrap.dedent(src)}
    for name, body in extra.items():
        files[f"pkg/{name}.py"] = textwrap.dedent(body)
    proj = Project.from_sources(files)
    pass_ = DeviceSyncPass(
        warm_suffixes=warm,
        allowed_syncs=allowed if allowed is not None else set(),
    )
    return run_passes(proj, [pass_])


def _rules(src, **kw):
    return [f.rule for f in _findings(src, **kw)]


class TestW013ImplicitSync:
    def test_flags_float_on_device_value(self):
        src = """
        import jax.numpy as jnp

        def scale(x):
            y = jnp.sum(x)
            return float(y)
        """
        found = _findings(src)
        assert [f.rule for f in found] == ["W013"]
        assert found[0].symbol == "scale"
        assert "float()" in found[0].message and found[0].hint

    def test_flags_item_and_np_asarray_on_device_values(self):
        src = """
        import jax.numpy as jnp
        import numpy as np

        def pull(x):
            t = jnp.max(x)
            host = np.asarray(t)
            return host

        def one(x):
            return jnp.argmax(x).item()
        """
        assert _rules(src) == ["W013", "W013"]

    def test_flags_block_until_ready_unconditionally(self):
        src = """
        import jax

        def fence(x):
            jax.block_until_ready(x)
            return x
        """
        found = _findings(src)
        assert [f.rule for f in found] == ["W013"]
        assert "block_until_ready" in found[0].message

    def test_allowlist_admits_the_sanctioned_fence(self):
        src = """
        import jax

        class Server:
            def execute(self, pending):
                jax.block_until_ready(pending)
                return pending
        """
        assert _rules(src, allowed={("warm.py", "Server.execute")}) == []
        # same code, no allowlist entry: flagged
        assert _rules(src) == ["W013"]

    def test_taint_flows_through_project_function_returns(self):
        src = """
        import jax.numpy as jnp

        def produce(x):
            return jnp.cumsum(x)

        def consume(x):
            r = produce(x)
            return int(r)
        """
        found = _findings(src)
        assert [f.rule for f in found] == ["W013"]
        assert found[0].symbol == "consume"

    def test_taint_flows_through_cross_module_returns(self):
        src = """
        from pkg.kernels import fused_sum

        def drain(x):
            r = fused_sum(x)
            return float(r)
        """
        kernels = """
        import jax.numpy as jnp

        def fused_sum(x):
            return jnp.sum(x)
        """
        assert _rules(src, kernels=kernels) == ["W013"]

    def test_quiet_after_device_get_sanitizer(self):
        src = """
        import jax
        import jax.numpy as jnp

        def ok(x):
            y = jnp.sum(x)
            host = jax.device_get(y)
            return float(host)
        """
        assert _rules(src) == []

    def test_quiet_on_metadata_attributes(self):
        src = """
        import jax.numpy as jnp

        def rows(x):
            y = jnp.add(x, 1)
            return int(y.shape[0]) + int(y.ndim)
        """
        assert _rules(src) == []

    def test_quiet_off_the_warm_path(self):
        src = """
        import jax.numpy as jnp

        def scale(x):
            return float(jnp.sum(x))
        """
        proj = Project.from_sources({"pkg/coldpath.py": textwrap.dedent(src)})
        out = run_passes(proj, [DeviceSyncPass(warm_suffixes=("warm.py",), allowed_syncs=set())])
        assert out == []


class TestW014HostBranchOnDeviceValue:
    def test_flags_if_on_device_value(self):
        src = """
        import jax.numpy as jnp

        def route(x):
            v = jnp.mean(x)
            if v > 0:
                return 1
            return 0
        """
        found = _findings(src)
        assert [f.rule for f in found] == ["W014"]
        assert found[0].symbol == "route"
        assert "jnp.where" in found[0].hint or "plan time" in found[0].hint

    def test_flags_while_on_device_value(self):
        src = """
        import jax.numpy as jnp

        def spin(x):
            err = jnp.max(x)
            while err > 1e-6:
                err = err * 0.5
            return err
        """
        assert _rules(src) == ["W014"]

    def test_quiet_when_branching_on_host_copy_or_none_check(self):
        src = """
        import jax
        import jax.numpy as jnp

        def route(x):
            v = jnp.mean(x)
            if x is None:
                return 0
            host = jax.device_get(v)
            if host > 0:
                return 1
            return 0
        """
        assert _rules(src) == []

    def test_traced_bodies_are_excluded(self):
        src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            y = jnp.sum(x)
            if y > 0:
                return y
            return -y
        """
        assert _rules(src) == []

    def test_function_passed_to_trace_wrapper_is_excluded(self):
        src = """
        import jax
        import jax.numpy as jnp

        def body(x):
            y = jnp.sum(x)
            return bool(y)

        def launch(x):
            return jax.lax.cond(True, body, body, x)
        """
        assert _rules(src) == []
