"""Timeseries engine tests: bucketed fetch, series combinators, pipeline
language — goldens computed in python."""
import numpy as np
import pytest

from pinot_tpu.query.engine import QueryEngine
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
from pinot_tpu.timeseries import TimeBuckets, TimeSeriesEngine, parse_pipeline

T0 = 1_700_000_000_000
MIN = 60_000
N = 20_000


def _schema():
    return Schema(
        "m",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("host", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )


@pytest.fixture(scope="module")
def env():
    rng = np.random.default_rng(71)
    data = {
        "city": rng.choice(["sf", "nyc"], N).astype(object),
        "host": rng.choice(["h1", "h2", "h3"], N).astype(object),
        "v": rng.integers(0, 100, N),
        "ts": T0 + rng.integers(0, 60 * MIN, N).astype(np.int64),
    }
    eng = QueryEngine()
    eng.register_table(_schema())
    eng.add_segment("m", build_segment(_schema(), data, "s0"))
    return TimeSeriesEngine(eng), data


def _golden(data, tags, buckets, reduce="sum", pred=None):
    out = {}
    for i in range(N):
        if pred is not None and not pred(i):
            continue
        b = buckets.bucket_of(data["ts"][i])
        if not (0 <= b < buckets.num):
            continue
        key = tuple(data[t][i] for t in tags)
        out.setdefault(key, {}).setdefault(b, []).append(int(data["v"][i]))
    series = {}
    for key, per in out.items():
        arr = np.full(buckets.num, np.nan)
        for b, vals in per.items():
            arr[b] = sum(vals) if reduce == "sum" else max(vals)
        series[key] = arr
    return series


def _close(a, b):
    return np.allclose(np.nan_to_num(a, nan=-1), np.nan_to_num(b, nan=-1))


class TestFetch:
    def test_bucketed_fetch_matches_golden(self, env):
        ts_eng, data = env
        buckets = TimeBuckets(T0, 5 * MIN, 12)
        plan = parse_pipeline("fetch table=m value=v agg=sum tags=city time=ts")
        block = ts_eng.execute(plan, buckets)
        golden = _golden(data, ["city"], buckets)
        assert set(block.series) == set(golden)
        for key in golden:
            assert _close(block.series[key], golden[key]), key

    def test_fetch_with_filter(self, env):
        ts_eng, data = env
        buckets = TimeBuckets(T0, 10 * MIN, 6)
        plan = parse_pipeline("fetch table=m value=v agg=sum filter=\"city = 'sf'\" tags=city time=ts")
        block = ts_eng.execute(plan, buckets)
        golden = _golden(data, ["city"], buckets, pred=lambda i: data["city"][i] == "sf")
        assert set(block.series) == {("sf",)}
        assert _close(block.series[("sf",)], golden[("sf",)])

    def test_partial_window(self, env):
        ts_eng, data = env
        # window covering only the first 15 minutes
        buckets = TimeBuckets(T0, 5 * MIN, 3)
        plan = parse_pipeline("fetch table=m value=v agg=max tags=host time=ts")
        block = ts_eng.execute(plan, buckets)
        golden = _golden(data, ["host"], buckets, reduce="max")
        for key in golden:
            assert _close(block.series[key], golden[key])


class TestCombinators:
    def test_sum_series_collapses_tags(self, env):
        ts_eng, data = env
        buckets = TimeBuckets(T0, 5 * MIN, 12)
        plan = parse_pipeline("fetch table=m value=v agg=sum tags=city,host time=ts | sumSeries city")
        block = ts_eng.execute(plan, buckets)
        golden = _golden(data, ["city"], buckets)
        assert set(block.series) == set(golden)
        for key in golden:
            assert _close(block.series[key], golden[key])

    def test_scale_and_global_sum(self, env):
        ts_eng, data = env
        buckets = TimeBuckets(T0, 15 * MIN, 4)
        plan = parse_pipeline("fetch table=m value=v agg=sum tags=city time=ts | sumSeries | scale 2")
        block = ts_eng.execute(plan, buckets)
        assert list(block.series) == [()]
        golden = _golden(data, [], buckets)
        assert _close(block.series[()], golden[()] * 2)

    def test_timestamps(self):
        b = TimeBuckets(T0, MIN, 5)
        assert b.timestamps() == [T0 + i * MIN for i in range(5)]
        assert b.end_ms == T0 + 5 * MIN
