"""MSE join tests: star joins vs sqlite on the 8-device CPU mesh.

Reference test-strategy parity: the golden-file join suites
(pinot-query-runtime/src/test/resources/queries/Joins.json checked against
H2, SURVEY.md 4.3) — here sqlite3 is the reference engine and the mock
cluster is the virtual 8-device mesh (SURVEY.md 4.5).
"""
import sqlite3

import numpy as np
import pytest

from pinot_tpu.mse import JoinPlanError, MultiStageEngine
from pinot_tpu.parallel.engine import DistributedEngine
from pinot_tpu.parallel.stacked import StackedTable
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema


def make_ssb(rng, n_fact=5000, n_dim=400):
    """Toy SSB: lineorder fact + date dimension."""
    datekeys = (19920101 + np.arange(n_dim) * 7).astype(np.int64)
    years = 1992 + (np.arange(n_dim) // 53).astype(np.int64)
    months = 1 + (np.arange(n_dim) % 12).astype(np.int64)
    date_schema = Schema(
        name="dates",
        fields=[
            FieldSpec("d_datekey", DataType.INT),
            FieldSpec("d_year", DataType.INT),
            FieldSpec("d_month", DataType.INT),
        ],
    )
    dates = {"d_datekey": datekeys, "d_year": years, "d_month": months}

    lo_schema = Schema(
        name="lineorder",
        fields=[
            FieldSpec("lo_orderdate", DataType.INT),
            FieldSpec("lo_revenue", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("lo_discount", DataType.INT, role=FieldRole.METRIC),
            FieldSpec("lo_region", DataType.STRING),
        ],
    )
    lineorder = {
        # ~10% of fact keys miss the dim table (exercise inner-join drops)
        "lo_orderdate": rng.choice(
            np.concatenate([datekeys, datekeys[:1] - 99]), n_fact
        ).astype(np.int64),
        "lo_revenue": rng.integers(1, 10_000, n_fact).astype(np.int64),
        "lo_discount": rng.integers(0, 11, n_fact).astype(np.int64),
        "lo_region": rng.choice(["asia", "europe", "americas"], n_fact),
    }
    return (lo_schema, lineorder), (date_schema, dates)


def sqlite_rows(lineorder, dates, sql):
    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE lineorder (lo_orderdate, lo_revenue, lo_discount, lo_region)")
    con.execute("CREATE TABLE dates (d_datekey, d_year, d_month)")
    con.executemany(
        "INSERT INTO lineorder VALUES (?,?,?,?)",
        list(zip(*(np.asarray(lineorder[c]).tolist() for c in
                   ("lo_orderdate", "lo_revenue", "lo_discount", "lo_region")))),
    )
    con.executemany(
        "INSERT INTO dates VALUES (?,?,?)",
        list(zip(*(np.asarray(dates[c]).tolist() for c in ("d_datekey", "d_year", "d_month")))),
    )
    rows = con.execute(sql).fetchall()
    con.close()
    return rows


@pytest.fixture(scope="module")
def engines():
    rng = np.random.default_rng(7)
    (lo_schema, lineorder), (date_schema, dates) = make_ssb(rng)
    eng = DistributedEngine()
    eng.register_table("lineorder", StackedTable.build(lo_schema, lineorder, eng.num_devices))
    eng.register_table("dates", StackedTable.build(date_schema, dates, eng.num_devices))
    return eng, lineorder, dates


STRATEGIES = ["broadcast", "shuffle"]


@pytest.mark.parametrize("strategy", STRATEGIES)
def test_join_groupby_dim_attr(engines, strategy):
    """BASELINE config 5: group by dim attribute, sum fact measure."""
    eng, lineorder, dates = engines
    sql = (
        "SELECT d_year, SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey "
        "GROUP BY d_year ORDER BY d_year LIMIT 100"
    )
    res = eng.query(f"SET joinStrategy = '{strategy}'; " + sql)
    exp = sqlite_rows(
        lineorder, dates,
        "SELECT d_year, SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey GROUP BY d_year ORDER BY d_year",
    )
    got = [(int(r[0]), int(r[1])) for r in res.rows]
    assert got == [(int(a), int(b)) for a, b in exp]


@pytest.mark.parametrize("strategy", STRATEGIES)
def test_join_filters_both_sides(engines, strategy):
    eng, lineorder, dates = engines
    res = eng.query(
        f"SET joinStrategy = '{strategy}'; "
        "SELECT d_year, COUNT(*), SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey "
        "WHERE lo_discount BETWEEN 1 AND 3 AND d_month <= 6 "
        "GROUP BY d_year ORDER BY d_year LIMIT 100"
    )
    exp = sqlite_rows(
        lineorder, dates,
        "SELECT d_year, COUNT(*), SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey "
        "WHERE lo_discount BETWEEN 1 AND 3 AND d_month <= 6 "
        "GROUP BY d_year ORDER BY d_year",
    )
    got = [(int(r[0]), int(r[1]), int(r[2])) for r in res.rows]
    assert got == [tuple(int(x) for x in r) for r in exp]


@pytest.mark.parametrize("strategy", STRATEGIES)
def test_join_scalar_agg(engines, strategy):
    eng, lineorder, dates = engines
    res = eng.query(
        f"SET joinStrategy = '{strategy}'; "
        "SELECT SUM(lo_revenue), COUNT(*) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey WHERE d_year = 1994"
    )
    exp = sqlite_rows(
        lineorder, dates,
        "SELECT SUM(lo_revenue), COUNT(*) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey WHERE d_year = 1994",
    )[0]
    assert int(res.rows[0][0]) == int(exp[0])
    assert int(res.rows[0][1]) == int(exp[1])


def test_join_groupby_order_trim_keeps_true_top(engines):
    """numGroupsLimit trim on the join group-by path must rank by the ORDER
    BY comparator (TableResizer analog), not lowest packed keys — the
    revenue skew below puts every true top group at HIGH d_datekey values
    (review-caught: the join path still used the lowest-key trim)."""
    eng, lineorder, dates = engines
    # d_datekey grows with index, and revenue correlates with the key, so
    # the lowest-key trim would keep exactly the WRONG groups
    rev = np.asarray(lineorder["lo_revenue"])
    od = np.asarray(lineorder["lo_orderdate"])
    skewed = dict(lineorder)
    skewed["lo_revenue"] = rev + (od - od.min()).astype(np.int64) * 1000
    eng2 = DistributedEngine()
    lo_schema = Schema(
        name="lineorder",
        fields=[
            FieldSpec("lo_orderdate", DataType.INT),
            FieldSpec("lo_revenue", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("lo_discount", DataType.INT, role=FieldRole.METRIC),
            FieldSpec("lo_region", DataType.STRING),
        ],
    )
    date_schema = Schema(
        name="dates",
        fields=[
            FieldSpec("d_datekey", DataType.INT),
            FieldSpec("d_year", DataType.INT),
            FieldSpec("d_month", DataType.INT),
        ],
    )
    eng2.register_table("lineorder", StackedTable.build(lo_schema, skewed, eng2.num_devices))
    eng2.register_table("dates", StackedTable.build(date_schema, dates, eng2.num_devices))
    sql = (
        "SELECT d_datekey, SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey "
        "GROUP BY d_datekey ORDER BY SUM(lo_revenue) DESC, d_datekey LIMIT 10"
    )
    res = eng2.query("SET numGroupsLimit = 40; " + sql)
    exp = sqlite_rows(
        skewed, dates,
        "SELECT d_datekey, SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey "
        "GROUP BY d_datekey ORDER BY SUM(lo_revenue) DESC, d_datekey LIMIT 10",
    )
    got = [(int(r[0]), int(r[1])) for r in res.rows]
    assert got == [(int(a), int(b)) for a, b in exp]


def test_join_groupby_mixed_fact_dim(engines):
    """Group keys from both sides of the join."""
    eng, lineorder, dates = engines
    res = eng.query(
        "SELECT lo_region, d_year, SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey "
        "GROUP BY lo_region, d_year ORDER BY lo_region, d_year LIMIT 1000"
    )
    exp = sqlite_rows(
        lineorder, dates,
        "SELECT lo_region, d_year, SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey "
        "GROUP BY lo_region, d_year ORDER BY lo_region, d_year",
    )
    got = [(r[0], int(r[1]), int(r[2])) for r in res.rows]
    assert got == [(a, int(b), int(c)) for a, b, c in exp]


def test_left_join_groupby(engines):
    eng, lineorder, dates = engines
    res = eng.query(
        "SELECT d_year, COUNT(*) FROM lineorder "
        "LEFT JOIN dates ON lo_orderdate = d_datekey "
        "GROUP BY d_year ORDER BY d_year NULLS LAST LIMIT 100"
    )
    exp = sqlite_rows(
        lineorder, dates,
        "SELECT d_year, COUNT(*) FROM lineorder "
        "LEFT JOIN dates ON lo_orderdate = d_datekey "
        "GROUP BY d_year ORDER BY d_year NULLS LAST",
    )
    got = [(None if r[0] is None else int(r[0]), int(r[1])) for r in res.rows]
    assert got == [(None if a is None else int(a), int(b)) for a, b in exp]


def test_qualified_refs_and_aliases(engines):
    eng, lineorder, dates = engines
    res = eng.query(
        "SELECT d.d_year, SUM(lo.lo_revenue) FROM lineorder lo "
        "JOIN dates d ON lo.lo_orderdate = d.d_datekey "
        "WHERE lo.lo_discount > 5 GROUP BY d.d_year ORDER BY d.d_year LIMIT 100"
    )
    exp = sqlite_rows(
        lineorder, dates,
        "SELECT d_year, SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey "
        "WHERE lo_discount > 5 GROUP BY d_year ORDER BY d_year",
    )
    got = [(int(r[0]), int(r[1])) for r in res.rows]
    assert got == [(int(a), int(b)) for a, b in exp]


def test_join_error_paths(engines):
    eng, _, _ = engines
    with pytest.raises(JoinPlanError):
        eng.query("SELECT COUNT(*) FROM lineorder JOIN nope ON lo_orderdate = d_datekey")
    with pytest.raises(JoinPlanError):
        # unknown alias qualifier
        eng.query(
            "SELECT x.d_year, COUNT(*) FROM lineorder JOIN dates ON lo_orderdate = d_datekey "
            "GROUP BY x.d_year"
        )
    with pytest.raises(NotImplementedError, match="joinMaxDup"):
        # many-to-many past the expansion cap (max multiplicity > 64)
        eng2 = DistributedEngine()
        rng = np.random.default_rng(0)
        s = Schema(name="dup", fields=[FieldSpec("k", DataType.INT), FieldSpec("v", DataType.INT)])
        eng2.register_table(
            "dup",
            StackedTable.build(
                s, {"k": rng.integers(0, 2, 640), "v": np.arange(640)}, eng2.num_devices
            ),
        )
        f = Schema(name="f", fields=[FieldSpec("fk", DataType.INT), FieldSpec("m", DataType.INT, role=FieldRole.METRIC)])
        eng2.register_table(
            "f",
            StackedTable.build(
                f, {"fk": rng.integers(0, 2, 64), "m": np.arange(64)}, eng2.num_devices
            ),
        )
        eng2.query("SELECT COUNT(*), SUM(m) FROM f JOIN dup ON fk = k")


def test_singletable_alias_qualifiers(engines):
    """alias.column on a NO-join query resolves (regression: raw KeyError)."""
    eng, lineorder, _ = engines
    res = eng.query("SELECT tt.lo_region, COUNT(*) FROM lineorder tt GROUP BY tt.lo_region ORDER BY tt.lo_region LIMIT 10")
    exp = {}
    for r in np.asarray(lineorder["lo_region"]):
        exp[r] = exp.get(r, 0) + 1
    got = {r[0]: int(r[1]) for r in res.rows}
    assert got == exp
    from pinot_tpu.sql.parser import SqlParseError

    with pytest.raises(SqlParseError):
        eng.query("SELECT nope.lo_region FROM lineorder tt LIMIT 1")


def test_bad_join_strategy_rejected(engines):
    eng, _, _ = engines
    with pytest.raises(ValueError, match="joinStrategy"):
        eng.query(
            "SET joinStrategy = 'hash'; SELECT COUNT(*) FROM lineorder "
            "JOIN dates ON lo_orderdate = d_datekey"
        )


@pytest.mark.parametrize("strategy", STRATEGIES)
def test_join_groupby_long_rawint_beyond_int32(strategy):
    """Regression: LONG metric group column with values past int32 must not
    wrap/crash in the MSE group-code paths."""
    rng = np.random.default_rng(3)
    n = 512
    base = 5_000_000_000
    fact_schema = Schema(
        name="f2",
        fields=[
            FieldSpec("fk", DataType.INT),
            FieldSpec("bucket", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("m", DataType.INT, role=FieldRole.METRIC),
        ],
    )
    fact = {
        "fk": rng.integers(0, 50, n).astype(np.int64),
        "bucket": (base + rng.integers(0, 4, n)).astype(np.int64),
        "m": rng.integers(0, 100, n).astype(np.int64),
    }
    dim_schema = Schema(
        name="d2", fields=[FieldSpec("dk", DataType.INT), FieldSpec("grp", DataType.INT)]
    )
    dim = {"dk": np.arange(50, dtype=np.int64), "grp": (np.arange(50) % 5).astype(np.int64)}
    eng = DistributedEngine()
    eng.register_table("f2", StackedTable.build(fact_schema, fact, eng.num_devices))
    eng.register_table("d2", StackedTable.build(dim_schema, dim, eng.num_devices))
    # tiny shards + 50 distinct keys skew the hash partition; widen slack
    res = eng.query(
        f"SET joinStrategy = '{strategy}'; SET shuffleSlack = 8; "
        "SELECT bucket, SUM(m) FROM f2 JOIN d2 ON fk = dk "
        "GROUP BY bucket ORDER BY bucket LIMIT 10"
    )
    exp = {}
    for b, m in zip(fact["bucket"], fact["m"]):
        exp[int(b)] = exp.get(int(b), 0) + int(m)
    got = {int(r[0]): int(r[1]) for r in res.rows}
    assert got == exp


def test_left_join_nullable_dim_attr_null_group():
    """Regression: LEFT JOIN group-by on a nullable dim attribute must merge
    stored-NULL rows and no-match rows into ONE SQL NULL group."""
    rng = np.random.default_rng(11)
    n = 256
    fact_schema = Schema(
        name="f3",
        fields=[FieldSpec("fk", DataType.INT), FieldSpec("m", DataType.INT, role=FieldRole.METRIC)],
    )
    fact = {"fk": rng.integers(0, 40, n).astype(np.int64), "m": np.ones(n, dtype=np.int64)}
    dim_schema = Schema(
        name="d3",
        fields=[FieldSpec("dk", DataType.INT), FieldSpec("dv", DataType.INT, nullable=True)],
    )
    dvals = [None if i % 3 == 0 else (10 if i % 2 else 20) for i in range(30)]  # dks 0..29 only
    dim = {"dk": np.arange(30, dtype=np.int64), "dv": np.array(dvals, dtype=object)}
    eng = DistributedEngine()
    eng.register_table("f3", StackedTable.build(fact_schema, fact, eng.num_devices))
    eng.register_table("d3", StackedTable.build(dim_schema, dim, eng.num_devices))
    res = eng.query(
        "SELECT dv, COUNT(*) FROM f3 LEFT JOIN d3 ON fk = dk GROUP BY dv ORDER BY dv NULLS LAST LIMIT 10"
    )
    exp = {}
    dmap = {i: dvals[i] for i in range(30)}
    for fk in fact["fk"]:
        v = dmap.get(int(fk))  # None for stored-NULL AND for fk >= 30
        exp[v] = exp.get(v, 0) + 1
    got = {r[0]: int(r[1]) for r in res.rows}
    assert got == exp


def test_shuffle_overflow_retries_to_exact_result(engines):
    """Tiny slack forces bucket overflow -> the engine's back-pressure loop
    re-plans with a doubled slack until the exchange fits, and the final
    result is EXACT (no silently dropped rows fold into the partials)."""
    from pinot_tpu.utils.metrics import METRICS

    eng, lineorder, dates = engines
    before = METRICS.counter("mse.exchangeOverflowRetries").value
    res = eng.query(
        "SET joinStrategy = 'shuffle'; SET shuffleSlack = 0.01; "
        "SELECT d_year, SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey GROUP BY d_year ORDER BY d_year LIMIT 100"
    )
    assert METRICS.counter("mse.exchangeOverflowRetries").value > before
    exp = sqlite_rows(
        lineorder, dates,
        "SELECT d_year, SUM(lo_revenue) FROM lineorder "
        "JOIN dates ON lo_orderdate = d_datekey GROUP BY d_year ORDER BY d_year",
    )
    got = [(int(r[0]), int(r[1])) for r in res.rows]
    assert got == [(int(a), int(b)) for a, b in exp]


def test_shuffle_overflow_gives_up_at_slack_cap(engines):
    """With the cap pinned at the starting slack the loop cannot back off ->
    clear give-up error naming the cap, not an infinite retry loop."""
    eng, _, _ = engines
    with pytest.raises(RuntimeError, match="shuffleSlackCap"):
        eng.query(
            "SET joinStrategy = 'shuffle'; SET shuffleSlack = 0.01; "
            "SET shuffleSlackCap = 0.01; "
            "SELECT d_year, SUM(lo_revenue) FROM lineorder "
            "JOIN dates ON lo_orderdate = d_datekey GROUP BY d_year"
        )


# ---------------------------------------------------------------------------
# Bounded many-to-many joins (range_join expansion, round 4)
# ---------------------------------------------------------------------------
def _mn_env(rng, n_fact=4000, n_keys=150):
    """Fact + a build side whose keys repeat (order -> MULTIPLE shipments)."""
    order_schema = Schema(
        name="orders",
        fields=[
            FieldSpec("o_key", DataType.INT),
            FieldSpec("o_rev", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    orders = {
        "o_key": rng.integers(0, n_keys, n_fact).astype(np.int64),
        "o_rev": rng.integers(1, 1000, n_fact).astype(np.int64),
    }
    # shipments: each key appears 0..5 times, with a carrier attribute
    reps = rng.integers(0, 6, n_keys)
    s_keys = np.repeat(np.arange(n_keys), reps).astype(np.int64)
    ship_schema = Schema(
        name="shipments",
        fields=[
            FieldSpec("s_key", DataType.INT),
            FieldSpec("s_carrier", DataType.STRING),
        ],
    )
    shipments = {
        "s_key": s_keys,
        "s_carrier": rng.choice(["ups", "dhl", "fedex"], len(s_keys)),
    }
    return (order_schema, orders), (ship_schema, shipments)


def _mn_sqlite(orders, shipments, sql):
    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE orders (o_key, o_rev)")
    con.execute("CREATE TABLE shipments (s_key, s_carrier)")
    con.executemany(
        "INSERT INTO orders VALUES (?,?)",
        list(zip(*(np.asarray(orders[c]).tolist() for c in ("o_key", "o_rev")))),
    )
    con.executemany(
        "INSERT INTO shipments VALUES (?,?)",
        list(zip(*(np.asarray(shipments[c]).tolist() for c in ("s_key", "s_carrier")))),
    )
    rows = con.execute(sql).fetchall()
    con.close()
    return rows


@pytest.fixture(scope="module")
def mn_engines():
    rng = np.random.default_rng(29)
    (os_, orders), (ss, shipments) = _mn_env(rng)
    eng = DistributedEngine()
    eng.register_table("orders", StackedTable.build(os_, orders, eng.num_devices))
    eng.register_table("shipments", StackedTable.build(ss, shipments, eng.num_devices))
    return eng, orders, shipments


class TestManyToManyJoin:
    def test_inner_mn_aggregation(self, mn_engines):
        """Each fact row contributes once PER matching build row."""
        eng, orders, shipments = mn_engines
        sql = (
            "SELECT COUNT(*), SUM(o_rev) FROM orders "
            "JOIN shipments ON o_key = s_key"
        )
        res = eng.query(sql + " LIMIT 10")
        exp = _mn_sqlite(orders, shipments, sql)
        assert (int(res.rows[0][0]), int(res.rows[0][1])) == (int(exp[0][0]), int(exp[0][1]))

    def test_inner_mn_groupby_build_attr(self, mn_engines):
        eng, orders, shipments = mn_engines
        sql = (
            "SELECT s_carrier, COUNT(*), SUM(o_rev) FROM orders "
            "JOIN shipments ON o_key = s_key GROUP BY s_carrier ORDER BY s_carrier"
        )
        res = eng.query(sql + " LIMIT 10")
        exp = _mn_sqlite(orders, shipments, sql)
        got = [(r[0], int(r[1]), int(r[2])) for r in res.rows]
        assert got == [(a, int(b), int(c)) for a, b, c in exp]

    def test_left_mn_keeps_unmatched(self, mn_engines):
        eng, orders, shipments = mn_engines
        sql = (
            "SELECT s_carrier, COUNT(*) FROM orders "
            "LEFT JOIN shipments ON o_key = s_key GROUP BY s_carrier ORDER BY s_carrier"
        )
        res = eng.query(sql + " LIMIT 10")
        exp = _mn_sqlite(orders, shipments, sql)
        got = {(r[0], int(r[1])) for r in res.rows}
        assert got == {(a, int(b)) for a, b in exp}

    def test_mn_with_filters(self, mn_engines):
        eng, orders, shipments = mn_engines
        sql = (
            "SELECT COUNT(*), SUM(o_rev) FROM orders "
            "JOIN shipments ON o_key = s_key "
            "WHERE o_rev > 500 AND s_carrier = 'ups'"
        )
        res = eng.query(sql + " LIMIT 10")
        exp = _mn_sqlite(orders, shipments, sql)
        got_cnt = int(res.rows[0][0])
        assert got_cnt == int(exp[0][0])
        if got_cnt:
            assert int(res.rows[0][1]) == int(exp[0][1])

    def test_shuffle_strategy_rejected_for_mn(self, mn_engines):
        eng, _, _ = mn_engines
        with pytest.raises(NotImplementedError, match="broadcast"):
            eng.query(
                "SET joinStrategy = 'shuffle'; "
                "SELECT COUNT(*) FROM orders JOIN shipments ON o_key = s_key LIMIT 5"
            )

    def test_range_join_end_clip_no_double_match(self):
        """A run ending exactly at the build array tail must not re-match
        its last row through the end clip (review-caught)."""
        import jax.numpy as jnp

        from pinot_tpu.mse.join import range_join

        rows, match = range_join(
            jnp.asarray([1, 2, 2, 3], dtype=jnp.int64),
            jnp.ones(4, dtype=bool),
            jnp.asarray([3], dtype=jnp.int64),
            max_dup=2,
        )
        assert bool(match[0, 0]) is True
        assert bool(match[0, 1]) is False  # key 3 appears once, not twice
