import time, functools
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

N = 1 << 27
G = 2406
rng = np.random.default_rng(0)
codes = rng.integers(0, G, N).astype(np.uint16)
quantity = rng.integers(1, 51, N).astype(np.uint8)
revenue = rng.integers(100, 1_000_000, N).astype(np.int32)
d = [jax.device_put(x) for x in (codes, quantity, revenue)]
W = 64
H = -(-G // W)

def kern_i8(codes, q, v, thresh, n_limbs=3, limb_bits=7, chunk=1<<20):
    mask = q < thresh
    vm = jnp.where(mask, v, 0).astype(jnp.uint32)
    limbs = [mask.astype(jnp.int8)]
    lb = np.uint32(limb_bits)
    for i in range(n_limbs):
        limbs.append(((vm >> (lb*np.uint32(i))) & np.uint32((1<<limb_bits)-1)).astype(jnp.int8))
    li = jnp.stack(limbs, axis=1)
    ki = codes.astype(jnp.int32)
    L = len(limbs)
    li = li.reshape(-1, chunk, L)
    ki = ki.reshape(-1, chunk)
    def body(acc, xs):
        l, kk = xs
        hi = kk // np.int32(W)
        lo = kk % np.int32(W)
        A = jax.nn.one_hot(hi, H, dtype=jnp.int8)
        B = jax.nn.one_hot(lo, W, dtype=jnp.int8)
        S = jnp.einsum("cl,ch,cw->lhw", l, A, B, preferred_element_type=jnp.int32)
        return acc + S.astype(jnp.float32), None
    acc, _ = lax.scan(body, jnp.zeros((L, H, W), jnp.float32), (li, ki))
    return acc.reshape(L, H*W)[:, :G]

def bench(name, f, K=8):
    @jax.jit
    def multi(codes, q, v):
        def body(i, acc):
            return acc + f(codes, q, v, (25 + i).astype(jnp.uint8)).sum()
        return lax.fori_loop(0, K, body, jnp.float32(0))
    @jax.jit
    def single(codes, q, v):
        return f(codes, q, v, jnp.uint8(25)).sum()
    out = multi(*d); jax.device_get(out)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); out = multi(*d); jax.device_get(out); ts.append(time.perf_counter()-t0)
    t_multi = float(np.median(ts))
    out = single(*d); jax.device_get(out)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); out = single(*d); jax.device_get(out); ts.append(time.perf_counter()-t0)
    t_single = float(np.median(ts))
    per_q = (t_multi - t_single)/(K-1)
    print(f"{name}: {per_q*1000:6.2f}ms  {N/per_q/1e9:5.2f} Grows/s")

bench("i8 3x7b chunk=1M", functools.partial(kern_i8, n_limbs=3, limb_bits=7, chunk=1<<20))
bench("i8 3x7b chunk=256K", functools.partial(kern_i8, n_limbs=3, limb_bits=7, chunk=1<<18))
bench("i8 3x7b chunk=64K", functools.partial(kern_i8, n_limbs=3, limb_bits=7, chunk=1<<16))
# correctness
out = jax.jit(functools.partial(kern_i8, n_limbs=3, limb_bits=7, chunk=1<<20))(*d, jnp.uint8(25))
r = np.asarray(jax.device_get(out), dtype=np.float64)
m = quantity < 25
exp_cnt = np.bincount(codes[m], minlength=G)
exp_sum = np.bincount(codes[m], weights=revenue[m].astype(np.float64), minlength=G)
got_sum = r[1] + r[2]*(1<<7) + r[3]*(1<<14)
print("count exact:", np.array_equal(r[0], exp_cnt), "sum exact:", np.array_equal(got_sum, exp_sum))
