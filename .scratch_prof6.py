import time, functools
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

N = 1 << 27
G = 2406
CHUNK = 1 << 16
rng = np.random.default_rng(0)
codes = rng.integers(0, G, N).astype(np.uint16)
quantity = rng.integers(1, 51, N).astype(np.uint8)
revenue = rng.integers(100, 1_000_000, N).astype(np.int32)
d = [jax.device_put(x) for x in (codes, quantity, revenue)]

def kern(codes, q, v, thresh, W, n_limbs, limb_bits=8, U=1, flat=False):
    H = -(-G // W)
    mask = q < thresh
    vm = jnp.where(mask, v, 0).astype(jnp.uint32)
    limbs = [mask.astype(jnp.bfloat16)]
    lb = np.uint32(limb_bits)
    for i in range(n_limbs):
        limbs.append(((vm >> (lb*np.uint32(i))) & np.uint32((1<<limb_bits)-1)).astype(jnp.bfloat16))
    li = jnp.stack(limbs, axis=1)
    ki = codes.astype(jnp.int32)
    L = len(limbs)
    C = CHUNK * U
    li = li.reshape(-1, C, L)
    ki = ki.reshape(-1, C)
    def body(acc, xs):
        l, kk = xs
        hi = kk // np.int32(W)
        lo = kk % np.int32(W)
        A = jax.nn.one_hot(hi, H, dtype=jnp.bfloat16)  # [C, H]
        B = jax.nn.one_hot(lo, W, dtype=jnp.bfloat16)  # [C, W]
        if flat:
            AL = (A[:, None, :] * l[:, :, None]).reshape(C, L*H)
            S = jnp.matmul(AL.T, B, preferred_element_type=jnp.float32)  # [L*H, W]
        else:
            S = jnp.einsum("cl,ch,cw->lhw", l, A, B, preferred_element_type=jnp.float32).reshape(L*H, W)
        return acc + S, None
    acc, _ = lax.scan(body, jnp.zeros((L*H, W), jnp.float32), (li, ki))
    return acc.reshape(L, H*W)[:, :G]

def bench(W, n_limbs, limb_bits=8, U=1, flat=False, K=8):
    f = functools.partial(kern, W=W, n_limbs=n_limbs, limb_bits=limb_bits, U=U, flat=flat)
    @jax.jit
    def multi(codes, q, v):
        def body(i, acc):
            return acc + f(codes, q, v, (25 + i).astype(jnp.uint8)).sum()
        return lax.fori_loop(0, K, body, jnp.float32(0))
    @jax.jit
    def single(codes, q, v):
        return f(codes, q, v, jnp.uint8(25)).sum()
    for fn, reps in ((multi, 3), (single, 3)):
        fn(*d) if fn is single else None
    out = multi(*d); jax.device_get(out)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); out = multi(*d); jax.device_get(out); ts.append(time.perf_counter()-t0)
    t_multi = float(np.median(ts))
    out = single(*d); jax.device_get(out)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); out = single(*d); jax.device_get(out); ts.append(time.perf_counter()-t0)
    t_single = float(np.median(ts))
    per_q = (t_multi - t_single)/(K-1)
    print(f"W={W:3d} limbs={n_limbs}x{limb_bits}b U={U} flat={int(flat)}: {per_q*1000:6.2f}ms  {N/per_q/1e9:5.2f} Grows/s")

bench(64, 3)
bench(128, 3)
bench(256, 3)
bench(128, 3, flat=True)
bench(128, 4, limb_bits=6, U=4)
bench(256, 4, limb_bits=6, U=4)
bench(128, 4, limb_bits=6, U=8)
print("--- limb scaling at W=64 ---")
bench(64, 1)
bench(64, 2)
bench(64, 5)
bench(64, 3, U=2)
