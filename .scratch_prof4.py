import time
import numpy as np
import jax, jax.numpy as jnp

N = 1 << 27
rng = np.random.default_rng(0)
v = rng.integers(100, 1_000_000, N).astype(np.int32)
dev = jax.devices()[0]
d_v = jax.device_put(v, dev)
print("committed:", d_v.committed)

@jax.jit
def sum1(x):
    return x.astype(jnp.float32).sum()

def bench(fn, *args, reps=6):
    out = fn(*args); jax.device_get(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = fn(*args); jax.device_get(out); ts.append(time.perf_counter()-t0)
    return ts

print("committed put:", [f"{t*1000:.1f}" for t in bench(sum1, d_v)])

# output of a jit as input (definitely device-resident)
@jax.jit
def ident(x):
    return x * 1
d_v2 = ident(d_v)
jax.device_get(d_v2[:8])
print("jit-output input:", [f"{t*1000:.1f}" for t in bench(sum1, d_v2)])
