import time, functools
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

N = 1 << 27
G = 2406
W = 64
CHUNK = 1 << 16
H = -(-G // W)
rng = np.random.default_rng(0)
codes = rng.integers(0, G, N).astype(np.uint16)
quantity = rng.integers(1, 51, N).astype(np.uint8)
revenue = rng.integers(100, 1_000_000, N).astype(np.int32)
d = [jax.device_put(x) for x in (codes, quantity, revenue)]

def one_query(codes, q, v, thresh, n_limbs=3):
    mask = q < thresh
    vm = jnp.where(mask, v, 0).astype(jnp.uint32)
    limbs = [mask.astype(jnp.bfloat16)]
    for i in range(n_limbs):
        limbs.append(((vm >> np.uint32(8*i)) & np.uint32(0xFF)).astype(jnp.bfloat16))
    li = jnp.stack(limbs, axis=1)
    ki = codes.astype(jnp.int32)
    L = len(limbs)
    li = li.reshape(-1, CHUNK, L)
    ki = ki.reshape(-1, CHUNK)
    def body(acc, xs):
        l, kk = xs
        hi = kk // np.int32(W)
        lo = kk % np.int32(W)
        A = jax.nn.one_hot(hi, H, dtype=jnp.bfloat16)
        B = jax.nn.one_hot(lo, W, dtype=jnp.bfloat16)
        S = jnp.einsum("cl,ch,cw->lhw", l, A, B, preferred_element_type=jnp.float32)
        return acc + S, None
    acc, _ = lax.scan(body, jnp.zeros((L, H, W), jnp.float32), (li, ki))
    return acc.reshape(L, H*W)[:, :G]

K = 10
@jax.jit
def multi(codes, q, v):
    def body(i, acc):
        out = one_query(codes, q, v, (25 + i).astype(jnp.uint8))
        return acc + out.sum()
    return lax.fori_loop(0, K, body, jnp.float32(0))

out = multi(*d); jax.device_get(out)
ts = []
for _ in range(3):
    t0 = time.perf_counter(); out = multi(*d); jax.device_get(out); ts.append(time.perf_counter()-t0)
t_multi = float(np.median(ts))

@jax.jit
def single(codes, q, v):
    return one_query(codes, q, v, jnp.uint8(25)).sum()
out = single(*d); jax.device_get(out)
ts = []
for _ in range(3):
    t0 = time.perf_counter(); out = single(*d); jax.device_get(out); ts.append(time.perf_counter()-t0)
t_single = float(np.median(ts))

per_query = (t_multi - t_single) / (K - 1)
print(f"single-call: {t_single*1000:.1f}ms; {K}-query call: {t_multi*1000:.1f}ms")
print(f"marginal per-query: {per_query*1000:.2f}ms -> {N/per_query/1e9:.2f} Grows/s")
