import time, functools
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

N = 1 << 27
G = 2406
W = 64
CHUNK = 1 << 16
rng = np.random.default_rng(0)
codes = rng.integers(0, G, N).astype(np.uint16)
quantity = rng.integers(1, 51, N).astype(np.uint8)
revenue = rng.integers(100, 1_000_000, N).astype(np.int32)

d_codes = jax.device_put(codes)
d_q = jax.device_put(quantity)
d_v = jax.device_put(revenue)

H = -(-G // W)

def fused(codes, q, v, n_limbs, unroll):
    mask = q < 25
    vm = jnp.where(mask, v, 0).astype(jnp.uint32)
    limbs = [mask.astype(jnp.bfloat16)]
    for i in range(n_limbs):
        limbs.append(((vm >> np.uint32(8*i)) & np.uint32(0xFF)).astype(jnp.bfloat16))
    li = jnp.stack(limbs, axis=1)  # [n, L]
    ki = codes.astype(jnp.int32)
    L = len(limbs)
    k = N // (CHUNK * unroll)
    li = li.reshape(k, unroll, CHUNK, L)
    ki = ki.reshape(k, unroll, CHUNK)
    def body(acc, xs):
        l, kk = xs
        hi = kk // np.int32(W)
        lo = kk % np.int32(W)
        A = jax.nn.one_hot(hi, H, dtype=jnp.bfloat16)
        B = jax.nn.one_hot(lo, W, dtype=jnp.bfloat16)
        S = jnp.einsum("ucl,uch,ucw->ulhw", l, A, B, preferred_element_type=jnp.float32)
        return acc + S.astype(jnp.float64).sum(0), None
    acc, _ = lax.scan(body, jnp.zeros((L, H, W), jnp.float64), (li, ki))
    acc = acc.reshape(L, H*W)[:, :G]
    cnt = acc[0]
    scales = jnp.asarray([float(1 << (8*i)) for i in range(n_limbs)], jnp.float64)
    s = (acc[1:] * scales[:, None]).sum(0)
    return cnt, s

def bench(fn, *args, reps=5):
    out = fn(*args); jax.device_get(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter(); out = fn(*args); jax.device_get(out); ts.append(time.perf_counter()-t0)
    return float(np.median(ts))

for unroll in (1, 2, 4, 8):
    f = jax.jit(functools.partial(fused, n_limbs=3, unroll=unroll))
    t = bench(f, d_codes, d_q, d_v)
    print(f"fused 3-limb unroll={unroll}: {t*1000:.1f}ms  {N/t/1e9:.2f} Grows/s")

# bandwidth ceiling: plain masked sum of all inputs
@jax.jit
def bw(codes, q, v):
    return (codes.astype(jnp.float32).sum(), q.astype(jnp.float32).sum(), v.astype(jnp.float32).sum())
t = bench(bw, d_codes, d_q, d_v)
print(f"bandwidth ref (sum all cols): {t*1000:.1f}ms  {N/t/1e9:.2f} Grows/s")

# correctness check vs numpy
cnt, s = jax.jit(functools.partial(fused, n_limbs=3, unroll=4))(d_codes, d_q, d_v)
m = quantity < 25
exp_cnt = np.bincount(codes[m], minlength=G)
exp_sum = np.bincount(codes[m], weights=revenue[m].astype(np.float64), minlength=G)
print("count exact:", np.array_equal(np.asarray(cnt), exp_cnt))
print("sum exact:", np.array_equal(np.asarray(s), exp_sum))
